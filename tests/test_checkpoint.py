"""Cluster-wide checkpoint/restore (r19, serve/checkpoint.py): the
torn-write-safe file format, boot-time warm restore with its staleness/
corruption/version cold-boot gates, the shed-purge-on-restore-install
rule, the blue-green import marker protocol with its LWW no-op
guarantee, the checkpoint fault points (a hung write never blocks
serving; a torn file restores cold, never crashes), the all-algorithm
at-least-as-restrictive restore property (token/leaky/sliding/GCRA),
restore across a GUBER_SHARDS change, and the ON==OFF differential
identity across the exact, single-device, and mesh pipelines.
"""

import asyncio
import json
import os

import grpc
import numpy as np
import pytest

from gubernator_tpu.api.grpc_glue import add_peers_servicer
from gubernator_tpu.api.types import (
    Algorithm,
    PeerInfo,
    RateLimitReq,
    Status,
    millisecond_now,
)
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve import checkpoint as ckpt_mod
from gubernator_tpu.serve import metrics
from gubernator_tpu.serve.backends import (
    ExactBackend,
    MeshBackend,
    TpuBackend,
)
from gubernator_tpu.serve.checkpoint import (
    CheckpointError,
    CheckpointManager,
    read_checkpoint,
    write_checkpoint,
)
from gubernator_tpu.serve.config import BehaviorConfig, ServerConfig
from gubernator_tpu.serve.faults import FAULTS
from gubernator_tpu.serve.instance import Instance
from gubernator_tpu.serve.replication import Snapshot

from tests.test_replication import (  # noqa: F401 (shared rig)
    FakeClock,
    _assert_same,
    _fuzz_stream,
    _pin_clock,
    _snap,
)

ADDR = "127.0.0.1:1"
T0 = 1_700_000_000_000


def _pin(monkeypatch, clock):
    _pin_clock(monkeypatch, clock)
    monkeypatch.setattr(ckpt_mod, "millisecond_now", clock)


def _req(key, hits=1, limit=5, duration=60_000,
         algo=Algorithm.TOKEN_BUCKET):
    return RateLimitReq(
        name="ckpt", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo,
    )


def _counter(metric, **labels) -> float:
    m = metric.labels(**labels) if labels else metric
    return m._value.get()


def _conf(**kw) -> ServerConfig:
    conf = ServerConfig(
        grpc_address=ADDR,
        advertise_address=ADDR,
        backend="exact",
        behaviors=BehaviorConfig(
            peer_timeout=0.2, peer_retries=0, peer_backoff=0.001,
            peer_backoff_max=0.002, breaker_failures=3,
            breaker_cooldown=0.2,
        ),
    )
    conf.checkpoint_interval = 60.0  # flushes driven manually
    for k, v in kw.items():
        setattr(conf, k, v)
    return conf


async def _instance(conf, backend=None) -> Instance:
    inst = Instance(
        conf, backend if backend is not None else ExactBackend(1000)
    )
    inst.start()
    await inst.set_peers([
        PeerInfo(address=conf.advertise_address, is_owner=True)
    ])
    return inst


# -- file format -------------------------------------------------------------


def _rows(n, now=None, **kw):
    now = millisecond_now() if now is None else now
    return [_snap(f"ck{i}", remaining=i, now=now, **kw) for i in range(n)]


def test_file_roundtrip_and_manifest(tmp_path):
    d = str(tmp_path)
    snaps = _rows(6000)  # > CHUNK_ROWS: multiple chunk files
    lanes = {c: list(range(7)) for c in ckpt_mod.LANE_COLS}
    write_checkpoint(d, snaps, lanes, "10.0.0.1:81", T0)
    manifest, got, got_lanes = read_checkpoint(d)
    assert manifest["format_version"] == ckpt_mod.FORMAT_VERSION
    assert manifest["advertise"] == "10.0.0.1:81"
    assert manifest["snapshot_ms"] == T0
    assert manifest["windows"] == 6000 and len(manifest["chunks"]) == 2
    assert got == snaps
    assert got_lanes == lanes
    # a SMALLER checkpoint over the same dir leaves no stale chunks
    write_checkpoint(d, snaps[:10], None, "10.0.0.1:81", T0 + 1)
    manifest2, got2, lanes2 = read_checkpoint(d)
    assert manifest2["windows"] == 10 and len(got2) == 10
    assert lanes2 is None
    files = sorted(os.listdir(d))
    assert files == ["chunk-0000.json", "manifest.json"]


def test_read_missing_manifest_is_cold_not_failure(tmp_path):
    assert read_checkpoint(str(tmp_path)) is None


def test_read_torn_chunk_raises_corrupt(tmp_path):
    d = str(tmp_path)
    write_checkpoint(d, _rows(100), None, "a:1", T0)
    p = os.path.join(d, "chunk-0000.json")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)  # torn write
    with pytest.raises(CheckpointError) as ei:
        read_checkpoint(d)
    assert ei.value.kind == "corrupt"


def test_read_missing_chunk_raises_read(tmp_path):
    d = str(tmp_path)
    write_checkpoint(d, _rows(3), None, "a:1", T0)
    os.remove(os.path.join(d, "chunk-0000.json"))
    with pytest.raises(CheckpointError) as ei:
        read_checkpoint(d)
    assert ei.value.kind == "read"


def test_read_future_format_version_refused(tmp_path):
    d = str(tmp_path)
    write_checkpoint(d, _rows(3), None, "a:1", T0)
    mp = os.path.join(d, "manifest.json")
    with open(mp) as f:
        m = json.load(f)
    m["format_version"] = ckpt_mod.FORMAT_VERSION + 1
    with open(mp, "w") as f:
        json.dump(m, f)
    with pytest.raises(CheckpointError) as ei:
        read_checkpoint(d)
    assert ei.value.kind == "version"


# -- boot-time restore -------------------------------------------------------


def test_restore_roundtrip_over_limit_survives_restart(tmp_path):
    """The headline contract: an over-limit window checkpointed by one
    process is still over-limit after a cold start of a NEW process
    pointed at the same directory — no quota amnesia."""

    async def run():
        d = str(tmp_path)
        a = await _instance(_conf(checkpoint_dir=d))
        b = None
        try:
            r = (await a.get_rate_limits([_req("hot", hits=9, limit=5)]))[0]
            assert r.status == Status.OVER_LIMIT
            reset = r.reset_time
            assert await a.checkpoint.flush_once() == 1
            # "SIGKILL": a simply stops; a fresh instance boots warm
            b = await _instance(_conf(checkpoint_dir=d))
            assert await b.checkpoint.restore() == 1
            r2 = (await b.get_rate_limits([_req("hot", hits=0, limit=5)]))[0]
            assert r2.status == Status.OVER_LIMIT
            assert r2.reset_time == reset, "restore opened a fresh window"
            # restored windows are tracked: the next flush re-captures
            assert b.checkpoint.tracked_len == 1
        finally:
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


def test_restore_stale_checkpoint_boots_cold(tmp_path, monkeypatch):
    clock = FakeClock()
    _pin(monkeypatch, clock)

    async def run():
        d = str(tmp_path)
        a = await _instance(_conf(checkpoint_dir=d))
        b = None
        try:
            await a.get_rate_limits([_req("hot", hits=9, limit=5)])
            await a.checkpoint.flush_once()
            clock.t += 301_000  # past GUBER_CHECKPOINT_MAX_AGE_MS
            before = _counter(metrics.CHECKPOINT_FAILURES, what="stale")
            b = await _instance(_conf(checkpoint_dir=d))
            assert await b.checkpoint.restore() == 0
            assert _counter(
                metrics.CHECKPOINT_FAILURES, what="stale"
            ) == before + 1
        finally:
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


def test_restore_zero_max_age_disables_the_gate(tmp_path, monkeypatch):
    clock = FakeClock()
    _pin(monkeypatch, clock)

    async def run():
        d = str(tmp_path)
        a = await _instance(_conf(checkpoint_dir=d))
        b = None
        try:
            await a.get_rate_limits(
                [_req("hot", hits=9, limit=5, duration=600_000)]
            )
            await a.checkpoint.flush_once()
            clock.t += 400_000  # stale by the default bound, window live
            b = await _instance(
                _conf(checkpoint_dir=d, checkpoint_max_age=0.0)
            )
            assert await b.checkpoint.restore() == 1
        finally:
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


def test_restore_torn_file_boots_cold_never_crashes(tmp_path):
    async def run():
        d = str(tmp_path)
        a = await _instance(_conf(checkpoint_dir=d))
        b = None
        try:
            await a.get_rate_limits([_req("hot", hits=9, limit=5)])
            await a.checkpoint.flush_once()
            p = os.path.join(d, "chunk-0000.json")
            with open(p, "r+b") as f:
                f.truncate(os.path.getsize(p) // 2)
            before = _counter(metrics.CHECKPOINT_FAILURES, what="corrupt")
            b = await _instance(_conf(checkpoint_dir=d))
            assert await b.checkpoint.restore() == 0
            assert _counter(
                metrics.CHECKPOINT_FAILURES, what="corrupt"
            ) == before + 1
            # cold but SERVING: the fresh window admits
            r = (await b.get_rate_limits([_req("hot", hits=1, limit=5)]))[0]
            assert r.error == "" and r.status == Status.UNDER_LIMIT
        finally:
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


def test_restore_version_skew_boots_cold(tmp_path):
    async def run():
        d = str(tmp_path)
        a = await _instance(_conf(checkpoint_dir=d))
        b = None
        try:
            await a.get_rate_limits([_req("hot", hits=9, limit=5)])
            await a.checkpoint.flush_once()
            mp = os.path.join(d, "manifest.json")
            with open(mp) as f:
                m = json.load(f)
            m["format_version"] = ckpt_mod.FORMAT_VERSION + 7
            with open(mp, "w") as f:
                json.dump(m, f)
            before = _counter(metrics.CHECKPOINT_FAILURES, what="version")
            b = await _instance(_conf(checkpoint_dir=d))
            assert await b.checkpoint.restore() == 0
            assert _counter(
                metrics.CHECKPOINT_FAILURES, what="version"
            ) == before + 1
        finally:
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


def test_restore_install_purges_stale_shed_verdict(tmp_path):
    """Satellite: a restored OVER window must not be shadowed by a
    pre-restore cached refusal — the bulk install path goes through
    Instance.update_peer_globals, whose shed purge fires for every
    installed key."""

    async def run():
        conf = _conf(
            checkpoint_dir=str(tmp_path), shed_cache=True,
            shed_cache_keys=128,
        )
        inst = await _instance(conf)
        try:
            # drain to zero, then freeze the refusal into the shed
            # cache (a frozen entry needs OVER_LIMIT with remaining 0)
            r0 = (await inst.get_rate_limits(
                [_req("shedk", hits=5, limit=5)]
            ))[0]
            assert r0.remaining == 0
            r = (await inst.get_rate_limits(
                [_req("shedk", hits=1, limit=5)]
            ))[0]
            assert r.status == Status.OVER_LIMIT and r.remaining == 0
            old_reset = r.reset_time
            assert inst.shed is not None and len(inst.shed) == 1
            # a restore install arrives for the same key with a NEWER
            # window (as after a restart whose checkpoint outlives the
            # cached verdict)
            now = millisecond_now()
            snap = _snap(
                _req("shedk").hash_key(), remaining=0,
                reset_time=old_reset + 30_000, now=now,
            )
            await inst.checkpoint.install("restore:test", [snap])
            # the stale cached verdict is GONE: the next answer carries
            # the restored window's reset_time, not the pre-install one
            r2 = (await inst.get_rate_limits(
                [_req("shedk", hits=1, limit=5)]
            ))[0]
            assert r2.status == Status.OVER_LIMIT
            assert r2.reset_time == old_reset + 30_000, (
                "stale shed-cache verdict served over the restored "
                "window"
            )
        finally:
            await inst.stop()

    asyncio.run(run())


# -- blue-green import marker protocol ---------------------------------------


def test_import_owned_installs_and_duplicate_delivery_noops():
    async def run():
        inst = await _instance(_conf(checkpoint_dir="/nonexistent-off"))
        try:
            now = millisecond_now()
            snap = _snap(_req("bg1").hash_key(), remaining=0,
                         reset_time=now + 40_000, now=now)
            await inst.checkpoint.install_import("import:blue:81", [snap])
            r = (await inst.get_rate_limits([_req("bg1", hits=0)]))[0]
            assert r.status == Status.OVER_LIMIT
            assert r.reset_time == now + 40_000
            # double delivery (every interval re-exports): a no-op
            await inst.checkpoint.install_import("import:blue:81", [snap])
            r2 = (await inst.get_rate_limits([_req("bg1", hits=0)]))[0]
            assert (r2.status, r2.remaining, r2.reset_time) == (
                r.status, r.remaining, r.reset_time
            )
        finally:
            await inst.stop()

    asyncio.run(run())


def test_import_nonowned_parks_and_seeds_on_ring_flip():
    """importfwd rows for keys this node does not own yet park in the
    LWW pending table; once the ring flips to make this node the
    owner, the first touch seeds the parked window (never a fresh
    one)."""
    from tests._util import free_ports

    async def run():
        conf = _conf(checkpoint_dir="/nonexistent-off")
        inst = await _instance(conf)
        try:
            # a second (dead) peer takes part of the ring; find a key
            # the DEAD peer owns
            for port in free_ports(16):
                dead = f"127.0.0.1:{port}"
                await inst.set_peers([
                    PeerInfo(address=ADDR, is_owner=True),
                    PeerInfo(address=dead, is_owner=False),
                ])
                key = next(
                    (f"bgp{i}" for i in range(200)
                     if not inst.get_peer(
                         _req(f"bgp{i}").hash_key()).is_owner),
                    None,
                )
                if key is not None:
                    break
            assert key is not None
            now = millisecond_now()
            newer = _snap(_req(key).hash_key(), remaining=0,
                          reset_time=now + 50_000, snapshot_ms=now + 1,
                          now=now)
            older = _snap(_req(key).hash_key(), remaining=3,
                          reset_time=now + 20_000, snapshot_ms=now,
                          now=now)
            # an importfwd batch is NEVER re-forwarded: the row parks
            await inst.checkpoint.install_import(
                "importfwd:blue:81", [newer]
            )
            assert inst.checkpoint.pending_len == 1
            # LWW: the older duplicate loses
            await inst.checkpoint.install_import(
                "importfwd:blue:81", [older]
            )
            assert inst.checkpoint.pending_len == 1
            parked = inst.checkpoint._pending[_req(key).hash_key()]
            assert parked.reset_time == now + 50_000
            # ring flips: this node now owns the key; the first touch
            # seeds the parked window
            await inst.set_peers([
                PeerInfo(address=ADDR, is_owner=True)
            ])
            r = (await inst.get_rate_limits([_req(key, hits=1)]))[0]
            assert r.status == Status.OVER_LIMIT
            assert r.reset_time == now + 50_000
            assert r.metadata["replicated"] == "true"
            assert inst.checkpoint.pending_len == 0
        finally:
            await inst.stop()

    asyncio.run(run())


def test_blue_green_export_over_real_grpc(tmp_path):
    """End-to-end cutover: the blue fleet's export lands the window on
    the green fleet over the real ReplicateBuckets door, and green
    answers OVER with blue's window before ever seeing the key."""
    from tests._util import free_ports
    from gubernator_tpu.serve.server import PeersV1Servicer

    async def run():
        port = next(iter(free_ports(1)))
        green_addr = f"127.0.0.1:{port}"
        green_conf = _conf(checkpoint_dir=str(tmp_path / "green"))
        green_conf.grpc_address = green_addr
        green_conf.advertise_address = green_addr
        green = await _instance(green_conf)
        blue = await _instance(_conf(
            checkpoint_dir=str(tmp_path / "blue"),
            checkpoint_export_peers=[green_addr],
        ))
        server = grpc.aio.server()
        add_peers_servicer(server, PeersV1Servicer(green))
        assert server.add_insecure_port(green_addr) != 0
        await server.start()
        try:
            r = (await blue.get_rate_limits(
                [_req("cutover", hits=9, limit=5)]
            ))[0]
            assert r.status == Status.OVER_LIMIT
            await blue.checkpoint.flush_once()  # interval tick / drain
            g = (await green.get_rate_limits(
                [_req("cutover", hits=0, limit=5)]
            ))[0]
            assert g.status == Status.OVER_LIMIT
            assert g.reset_time == r.reset_time
        finally:
            await server.stop(None)
            await blue.stop()
            await green.stop()

    asyncio.run(run())


# -- fault injection ---------------------------------------------------------


def test_hung_checkpoint_write_never_blocks_serving(tmp_path):
    async def run():
        FAULTS.configure("checkpoint_write:hang")
        conf = _conf(checkpoint_dir=str(tmp_path))
        conf.checkpoint_interval = 0.02
        inst = await _instance(conf)
        try:
            await inst.get_rate_limits([_req("hk", hits=9, limit=5)])
            inst.checkpoint.kick()
            await asyncio.sleep(0.1)  # the flush loop is now parked
            for i in range(20):
                r = (await inst.get_rate_limits(
                    [_req("hk", hits=1, limit=5)]
                ))[0]
                assert r.error == "" and r.status == Status.OVER_LIMIT
            # the hang really fired (not a vacuous pass)
            assert _counter(
                metrics.FAULTS_INJECTED,
                point="checkpoint_write", action="hang",
            ) >= 1
            # and nothing landed on disk while parked
            assert not os.path.exists(
                os.path.join(str(tmp_path), "manifest.json")
            )
        finally:
            FAULTS.clear()
            await inst.stop()

    asyncio.run(run())


def test_checkpoint_write_error_counts_and_serving_continues(tmp_path):
    async def run():
        conf = _conf(checkpoint_dir=str(tmp_path))
        inst = await _instance(conf)
        try:
            await inst.get_rate_limits([_req("we", hits=1, limit=5)])
            FAULTS.configure("checkpoint_write:error")
            before = _counter(metrics.CHECKPOINT_FAILURES, what="write")
            await inst.checkpoint.flush_once()  # must not raise
            assert _counter(
                metrics.CHECKPOINT_FAILURES, what="write"
            ) == before + 1
            FAULTS.clear()
            # recovery: the next flush writes a usable checkpoint
            await inst.checkpoint.flush_once()
            manifest, snaps, _ = read_checkpoint(str(tmp_path))
            assert manifest["windows"] == len(snaps) == 1
        finally:
            FAULTS.clear()
            await inst.stop()

    asyncio.run(run())


def test_checkpoint_read_fault_boots_cold(tmp_path):
    async def run():
        d = str(tmp_path)
        a = await _instance(_conf(checkpoint_dir=d))
        b = None
        try:
            await a.get_rate_limits([_req("rf", hits=9, limit=5)])
            await a.checkpoint.flush_once()
            FAULTS.configure("checkpoint_read:error")
            before = _counter(metrics.CHECKPOINT_FAILURES, what="read")
            b = await _instance(_conf(checkpoint_dir=d))
            assert await b.checkpoint.restore() == 0
            assert _counter(
                metrics.CHECKPOINT_FAILURES, what="read"
            ) == before + 1
            r = (await b.get_rate_limits([_req("rf", hits=0)]))[0]
            assert r.error == ""
        finally:
            FAULTS.clear()
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


def test_fault_spec_grammar_knows_checkpoint_points():
    from gubernator_tpu.serve.faults import parse_fault_spec

    rules = parse_fault_spec(
        "checkpoint_write:delay=50ms,checkpoint_read:error"
    )
    assert [(r.point, r.action) for r in rules] == [
        ("checkpoint_write", "delay"), ("checkpoint_read", "error"),
    ]
    with pytest.raises(ValueError, match="unknown fault point"):
        parse_fault_spec("checkpoint_flush:error")


# -- all-algorithm restore property ------------------------------------------


def _mixed_reqs(n=32, duration=60_000):
    """Every algorithm, with some keys driven past their limit."""
    reqs = []
    for i in range(n):
        algo = Algorithm(i % 4)
        over = (i % 8) >= 4
        reqs.append(RateLimitReq(
            name="ckpt", unique_key=f"mx{i}",
            hits=9 if over else 2, limit=5 if over else 10,
            duration=duration, algorithm=algo,
        ))
    return reqs


def _device_conf(tmp_path):
    c = _conf(checkpoint_dir=str(tmp_path), backend="tpu")
    return c


@pytest.mark.parametrize("mesh", [False, True])
def test_restore_all_algorithms_at_least_as_restrictive(
    tmp_path, monkeypatch, mesh
):
    """The satellite property, pinned byte-exact: every restored
    window (token, leaky, sliding, GCRA — the full-lane section)
    answers EXACTLY what the pre-kill window answered at the same
    clock; restored remaining never exceeds the pre-kill oracle."""
    import jax

    clock = FakeClock()
    _pin(monkeypatch, clock)

    def be():
        if mesh:
            return MeshBackend(
                StoreConfig(rows=4, slots=256),
                devices=jax.devices(),
                buckets=(16, 64),
            )
        return TpuBackend(
            StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
        )

    async def run():
        a = await _instance(_device_conf(tmp_path), backend=be())
        b = None
        try:
            reqs = _mixed_reqs()
            await a.get_rate_limits(reqs)
            clock.t += 500
            await a.get_rate_limits(reqs)  # second round: real state
            peeks = [
                RateLimitReq(
                    name="ckpt", unique_key=r.unique_key, hits=0,
                    limit=r.limit, duration=r.duration,
                    algorithm=r.algorithm,
                ) for r in reqs
            ]
            oracle = await a.get_rate_limits(peeks)
            await a.checkpoint.flush_once()
            # SIGKILL the fleet; a new process restores from disk
            b = await _instance(_device_conf(tmp_path), backend=be())
            # not every request persists a window (a refusal on the
            # insufficient-remaining path stores nothing), but most do
            restored = await b.checkpoint.restore()
            assert restored >= len(reqs) * 3 // 4
            got = await b.get_rate_limits(peeks)
            for r, x, y in zip(reqs, oracle, got):
                _assert_same(x, y, r)
                assert y.remaining <= x.remaining
        finally:
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


def test_restore_across_shard_count_change(tmp_path, monkeypatch):
    """Restore is also a re-partition: a checkpoint taken under an
    8-shard mesh restores byte-exact into a 4-shard mesh (the lanes
    install routes by hash under the CURRENT ShardingPolicy)."""
    import jax

    clock = FakeClock()
    _pin(monkeypatch, clock)
    devs = jax.devices()
    assert len(devs) >= 8

    async def run():
        a = await _instance(
            _device_conf(tmp_path),
            backend=MeshBackend(
                StoreConfig(rows=4, slots=256), devices=devs[:8],
                buckets=(16, 64),
            ),
        )
        b = None
        try:
            reqs = _mixed_reqs()
            await a.get_rate_limits(reqs)
            peeks = [
                RateLimitReq(
                    name="ckpt", unique_key=r.unique_key, hits=0,
                    limit=r.limit, duration=r.duration,
                    algorithm=r.algorithm,
                ) for r in reqs
            ]
            oracle = await a.get_rate_limits(peeks)
            await a.checkpoint.flush_once()
            b = await _instance(
                _device_conf(tmp_path),
                backend=MeshBackend(
                    StoreConfig(rows=4, slots=256), devices=devs[:4],
                    buckets=(16, 64),
                ),
            )
            assert await b.checkpoint.restore() >= len(reqs) * 3 // 4
            got = await b.get_rate_limits(peeks)
            for r, x, y in zip(reqs, oracle, got):
                _assert_same(x, y, r)
        finally:
            await a.stop()
            if b is not None:
                await b.stop()

    asyncio.run(run())


# -- differential identity: checkpoint ON == OFF -----------------------------


async def _ckpt_fuzz_pair(mk_backend, clock, steps, seed, tmp_path):
    """ON and OFF twins, identical single-node ring, only the knob
    differs; the ON twin flushes (disk write + lanes gather) every 25
    steps. Responses must stay byte-identical — captures are
    non-mutating and writes happen off the request path."""
    keys = [f"cf{i}" for i in range(12)]

    async def mk(ckpt_dir):
        conf = _conf(checkpoint_dir=ckpt_dir)
        inst = Instance(conf, mk_backend())
        inst.start()
        await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
        return inst

    on = await mk(str(tmp_path))
    off = await mk("")
    assert on.checkpoint is not None and off.checkpoint is None
    try:
        rng = np.random.default_rng(seed)
        flushed = 0
        for step, batch, dt in _fuzz_stream(rng, keys, steps):
            clock.t += dt
            a = await on.get_rate_limits(batch)
            b = await off.get_rate_limits(batch)
            for x, y, r in zip(a, b, batch):
                _assert_same(x, y, (step, r))
            if step % 25 == 24:
                flushed += await on.checkpoint.flush_once()
        assert flushed > 0, "fuzz never captured a tracked window"
    finally:
        await on.stop()
        await off.stop()


@pytest.mark.parametrize("seed", [3, 11])
def test_differential_identity_fuzz_exact(tmp_path, monkeypatch, seed):
    clock = FakeClock()
    _pin(monkeypatch, clock)
    asyncio.run(_ckpt_fuzz_pair(
        lambda: ExactBackend(10_000), clock, 250, seed, tmp_path
    ))


def test_differential_identity_fuzz_device(tmp_path, monkeypatch):
    clock = FakeClock()
    _pin(monkeypatch, clock)

    def be():
        return TpuBackend(StoreConfig(rows=16, slots=1 << 10),
                          buckets=(16, 64))

    asyncio.run(_ckpt_fuzz_pair(be, clock, 100, 5, tmp_path))


def test_differential_identity_fuzz_mesh(tmp_path, monkeypatch):
    import jax

    clock = FakeClock()
    _pin(monkeypatch, clock)

    def be():
        return MeshBackend(
            StoreConfig(rows=4, slots=256), devices=jax.devices(),
            buckets=(16, 64),
        )

    asyncio.run(_ckpt_fuzz_pair(be, clock, 60, 7, tmp_path))


# -- manager tables / config gates -------------------------------------------


def test_tracked_eviction_keeps_freshest():
    async def run():
        conf = _conf(checkpoint_dir="/x", checkpoint_track_keys=2)
        m = CheckpointManager(conf, None)
        m.note_owned(_req("a"))
        m.note_owned(_req("b"))
        m.note_owned(_req("a"))  # refresh: b is now stalest
        m.note_owned(_req("c"))
        assert sorted(m._tracked) == sorted(
            [_req("a").hash_key(), _req("c").hash_key()]
        )
        # peeks and non-token algorithms never track
        m.note_owned(_req("d", hits=0))
        m.note_owned(_req("e", algo=Algorithm.LEAKY_BUCKET))
        assert len(m._tracked) == 2

    asyncio.run(run())


def test_checkpoint_refused_without_snapshot_surface():
    class _NoSnap:
        inline_decide = True

        def decide(self, reqs, gnp, now=None):  # pragma: no cover
            return []

    with pytest.raises(ValueError, match="GUBER_CHECKPOINT"):
        Instance(_conf(checkpoint_dir="/x"), _NoSnap())


def test_config_knobs_parse_and_validate():
    from gubernator_tpu.serve.config import config_from_env

    conf = config_from_env({
        "GUBER_CHECKPOINT_DIR": "/var/lib/guber/ckpt",
        "GUBER_CHECKPOINT_INTERVAL_MS": "2500",
        "GUBER_CHECKPOINT_MAX_AGE_MS": "120000",
        "GUBER_CHECKPOINT_TRACK_KEYS": "1024",
        "GUBER_CHECKPOINT_EXPORT_PEERS": "10.0.0.9:81, 10.0.0.10:81",
    })
    assert conf.checkpoint_dir == "/var/lib/guber/ckpt"
    assert conf.checkpoint_interval == 2.5
    assert conf.checkpoint_max_age == 120.0
    assert conf.checkpoint_track_keys == 1024
    assert conf.checkpoint_export_peers == [
        "10.0.0.9:81", "10.0.0.10:81"
    ]
    with pytest.raises(ValueError, match="GUBER_CHECKPOINT_INTERVAL_MS"):
        config_from_env({"GUBER_CHECKPOINT_INTERVAL_MS": "0"})
    with pytest.raises(ValueError, match="GUBER_CHECKPOINT_MAX_AGE_MS"):
        config_from_env({"GUBER_CHECKPOINT_MAX_AGE_MS": "-1"})
    with pytest.raises(ValueError, match="GUBER_CHECKPOINT_TRACK_KEYS"):
        config_from_env({"GUBER_CHECKPOINT_TRACK_KEYS": "0"})
