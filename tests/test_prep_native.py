"""Native one-call batch prep (guber_prep_sharded) differential tests.

The mesh serving hot path builds its per-shard device arrays through ONE
native call (presort + duplicate-key groups + clipped/padded marshal,
optionally thread-parallel — guberhash.cc). These tests pin it
bit-identical to the pure-numpy twin (parallel/sharded.py fallbacks /
engine.build_groups) across batch shapes, shard counts, store sizes, and
pool widths; the twin is itself pinned against the kernel's contract by
tests/test_sharded.py and tests/test_kernels.py.
"""

import logging
import os
import subprocess
import sys

import numpy as np
import pytest

from gubernator_tpu.core.engine import dense_ladder_extension
from gubernator_tpu.core.store import (
    COUNTER_MAX,
    MAX_DURATION_MS,
    TIME_FLOOR,
)
import gubernator_tpu.parallel.sharded as sh

hn = pytest.importorskip(
    "gubernator_tpu.native.hashlib_native", reason="native lib not built"
)
if not getattr(hn, "_HAS_PREP", False):
    pytest.skip(
        "libguberhash.so predates guber_prep_sharded",
        allow_module_level=True,
    )


def _traffic(rng, n):
    zipf = rng.zipf(1.2, size=n) % 50_000
    kh = (
        zipf.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    ) ^ np.uint64(0xABCD)
    return (
        kh,
        rng.integers(-(2**40), 2**40, n),  # hits: exercises clipping
        rng.integers(0, 2**40, n),
        rng.integers(-5, 2**40, n),  # duration: below TIME_FLOOR too
        rng.integers(0, 2, n).astype(np.int32),
        rng.integers(0, 2, n).astype(bool),
    )


def _numpy_twin(sub, slots, ns, arrays, group_rung=None):
    saved = sh._presort_sharded_grouped, sh._prep_native
    sh._presort_sharded_grouped = sh._np_presort_sharded_grouped
    sh._prep_native = None
    try:
        return sh.pad_request_sharded(
            sub, slots, ns, *arrays, with_groups=True,
            group_rung=group_rung,
        )
    finally:
        sh._presort_sharded_grouped, sh._prep_native = saved


CONFIGS = [
    (32768, 8, 1 << 15),  # flagship mesh shape
    (1000, 8, 1 << 15),
    (5000, 6, 1 << 12),  # non-power-of-two shards
    (64, 3, 256),
    (1, 8, 1 << 15),  # 7 empty shards
    (17, 2, 1024),
    (4096, 1, 1 << 15),  # single-device form
    (32768, 16, 1 << 15),
    (300, 8, 1 << 15),
    (8192, 4, 1 << 10),
    (2, 8, 64),  # mostly-empty tiny store
    (128, 128, 1 << 15),  # many shards, some empty
]


@pytest.mark.parametrize("n,ns,slots", CONFIGS)
def test_prep_matches_numpy_twin(n, ns, slots):
    logging.disable(logging.WARNING)  # ladder-overflow warning is expected
    try:
        rng = np.random.default_rng(hash((n, ns, slots)) % 2**32)
        arrays = _traffic(rng, n)
        sub = sh.sub_batch_ladder((64, 256, 1024, 4096))
        req_np, order_np, take_np, groups_np = _numpy_twin(
            sub, slots, ns, arrays
        )
        rungs = np.asarray(dense_ladder_extension(sub, n), np.int64)
        order, counts, take, fields, groups, B, G = hn.prep_sharded(
            *arrays, slots, ns, rungs, 0,
            -COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS,
        )
        assert B == req_np.key_hash.shape[1]
        assert G == groups_np.key_hash.shape[1]
        assert int(counts.sum()) == n
        np.testing.assert_array_equal(order, order_np[:n])
        np.testing.assert_array_equal(take, take_np)
        for f in (
            "key_hash", "hits", "limit", "duration", "algo", "gnp", "valid"
        ):
            np.testing.assert_array_equal(
                fields[f], getattr(req_np, f), err_msg=f
            )
        for f in ("key_hash", "leader_pos", "end_pos", "valid", "group_id"):
            np.testing.assert_array_equal(
                groups[f], getattr(groups_np, f), err_msg=f"groups.{f}"
            )
    finally:
        logging.disable(logging.NOTSET)


def test_prep_group_rung_override_and_error():
    rng = np.random.default_rng(7)
    n, ns, slots = 2048, 4, 1 << 12
    arrays = _traffic(rng, n)
    sub = sh.sub_batch_ladder((64, 256, 1024, 4096))
    rungs = np.asarray(dense_ladder_extension(sub, n), np.int64)
    clip = (-COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS)
    # a valid override is honored exactly
    *_, G = hn.prep_sharded(*arrays, slots, ns, rungs, 1024, *clip)
    assert G == 1024
    req_np, _o, _t, groups_np = _numpy_twin(
        sub, slots, ns, arrays, group_rung=1024
    )
    assert groups_np.key_hash.shape[1] == 1024
    # an override below a shard's group count raises like the numpy path
    with pytest.raises(ValueError, match="group_rung"):
        hn.prep_sharded(*arrays, slots, ns, rungs, 1, *clip)
    with pytest.raises(ValueError, match="group_rung"):
        _numpy_twin(sub, slots, ns, arrays, group_rung=1)


def test_prep_buffer_lifetime_two_generations():
    """Results stay intact across ONE further call (the pipelined
    engine's two-in-flight bound) and are recycled after two."""
    rng = np.random.default_rng(11)
    sub = sh.sub_batch_ladder((64, 256, 1024, 4096))
    clip = (-COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS)
    a1 = _traffic(rng, 500)
    a2 = _traffic(rng, 500)
    rungs = np.asarray(dense_ladder_extension(sub, 500), np.int64)
    r1 = hn.prep_sharded(*a1, 1 << 12, 4, rungs, 0, *clip)
    kh1 = r1[3]["key_hash"].copy()
    hn.prep_sharded(*a2, 1 << 12, 4, rungs, 0, *clip)  # generation flips
    np.testing.assert_array_equal(r1[3]["key_hash"], kh1)


@pytest.mark.parametrize("threads", ["2", "4", "7"])
def test_prep_thread_pool_bit_identity(threads):
    """GUBER_PREP_THREADS is resolved at pool creation, so the threaded
    runs execute in a subprocess; output must be bit-identical to the
    in-process single-thread result for a fixed seed."""
    code = """
import numpy as np, sys
from gubernator_tpu.native import hashlib_native as hn
from gubernator_tpu.core.engine import dense_ladder_extension
from gubernator_tpu.core.store import COUNTER_MAX, MAX_DURATION_MS, TIME_FLOOR
import gubernator_tpu.parallel.sharded as sh
rng = np.random.default_rng(99)
n, ns, slots = 20000, 8, 1 << 15
zipf = rng.zipf(1.2, size=n) % 50_000
kh = (zipf.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)) ^ np.uint64(0xF00)
hits = rng.integers(-2**40, 2**40, n); limit = rng.integers(0, 2**40, n)
dur = rng.integers(-5, 2**40, n); algo = rng.integers(0, 2, n).astype(np.int32)
gnp = rng.integers(0, 2, n).astype(bool)
sub = sh.sub_batch_ladder((64, 256, 1024, 4096))
rungs = np.asarray(dense_ladder_extension(sub, n), np.int64)
r = hn.prep_sharded(kh, hits, limit, dur, algo, gnp, slots, ns, rungs, 0,
                    -COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS)
assert hn.prep_threads() == int(sys.argv[1]), hn.prep_threads()
import hashlib
d = hashlib.sha256()
for a in (r[0], r[2], r[3]["key_hash"], r[3]["hits"], r[3]["valid"],
          r[4]["leader_pos"], r[4]["end_pos"], r[4]["group_id"]):
    d.update(np.ascontiguousarray(a).tobytes())
print(d.hexdigest())
"""
    env = dict(os.environ, GUBER_PREP_THREADS="1", PYTHONPATH=".")
    base = subprocess.run(
        [sys.executable, "-c", code, "1"],
        capture_output=True, text=True, env=env, check=True,
    ).stdout.strip()
    env["GUBER_PREP_THREADS"] = threads
    got = subprocess.run(
        [sys.executable, "-c", code, threads],
        capture_output=True, text=True, env=env, check=True,
    ).stdout.strip()
    assert got == base, f"threads={threads} diverged"


def test_single_device_prep_matches_numpy_twin():
    """engine.pad_request_sorted's native gate (multi-thread hosts) must
    be bit-identical to its numpy/fused path regardless of the gate, so
    exercise the n_shards=1 native form directly."""
    import gubernator_tpu.core.engine as eng

    rng = np.random.default_rng(21)
    n, slots = 4096, 1 << 15
    arrays = _traffic(rng, n)
    saved = eng._hn
    eng._hn = None
    try:
        req_np, order_np, groups_np = eng.pad_request_sorted(
            (4096,), slots, *arrays, with_groups=True
        )
    finally:
        eng._hn = saved
    clip = (-COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS)
    order, _c, _t, fields, groups, B, G = hn.prep_sharded(
        *arrays, slots, 1, np.asarray([4096], np.int64), 0, *clip
    )
    assert B == 4096
    np.testing.assert_array_equal(order, order_np[:n])
    for f in ("key_hash", "hits", "limit", "duration", "algo", "gnp", "valid"):
        np.testing.assert_array_equal(
            fields[f][0], getattr(req_np, f), err_msg=f
        )
    for f in ("key_hash", "leader_pos", "end_pos", "valid", "group_id"):
        np.testing.assert_array_equal(
            groups[f][0], getattr(groups_np, f), err_msg=f"groups.{f}"
        )


def test_engine_native_gate_glue_multithread():
    """The pad_request_sorted native branch only runs when
    prep_threads() > 1 (never on this 1-core box in-process), so drive
    it in a subprocess with GUBER_PREP_THREADS=2 and assert its output
    equals the numpy path computed in the same process."""
    code = """
import numpy as np
import gubernator_tpu.core.engine as eng
from gubernator_tpu.native import hashlib_native as hn
assert hn.prep_threads() == 2
rng = np.random.default_rng(33)
n, slots = 5000, 1 << 14
zipf = rng.zipf(1.2, size=n) % 20_000
kh = (zipf.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)) ^ np.uint64(0x77)
arrays = (kh, rng.integers(-2**40, 2**40, n), rng.integers(0, 2**40, n),
          rng.integers(-5, 2**40, n), rng.integers(0, 2, n).astype(np.int32),
          rng.integers(0, 2, n).astype(bool))
args = ((64, 256, 1024, 4096, 8192), slots) + arrays
req, order, groups = eng.pad_request_sorted(*args, with_groups=True)
# copy before the twin runs (twin path doesn't flip buffers, but be safe)
native = [np.array(x) for x in (order, *req, *groups)]
saved = eng._hn
eng._hn = None
req_np, order_np, groups_np = eng.pad_request_sorted(*args, with_groups=True)
eng._hn = saved
for got, want in zip(native, (order_np, *req_np, *groups_np)):
    np.testing.assert_array_equal(got, want)
print("GLUE-OK")
"""
    env = dict(os.environ, GUBER_PREP_THREADS="2", PYTHONPATH=".")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr[-800:]
    assert "GLUE-OK" in out.stdout


def test_prep_pool_fork_safety():
    """A forked child inherits a multi-lane pool with no worker threads;
    the atfork guard must make it run inline instead of hanging."""
    code = """
import os, sys
import numpy as np
from gubernator_tpu.native import hashlib_native as hn
from gubernator_tpu.core.engine import dense_ladder_extension
from gubernator_tpu.core.store import COUNTER_MAX, MAX_DURATION_MS, TIME_FLOOR
import gubernator_tpu.parallel.sharded as sh
rng = np.random.default_rng(5)
n = 4000
kh = rng.integers(1, 2**63, n).astype(np.uint64)
arrays = (kh, np.ones(n, np.int64), np.ones(n, np.int64) * 10,
          np.ones(n, np.int64) * 1000, np.zeros(n, np.int32),
          np.zeros(n, bool))
sub = sh.sub_batch_ladder((64, 256, 1024, 4096))
rungs = np.asarray(dense_ladder_extension(sub, n), np.int64)
clip = (-COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS)
r_parent = hn.prep_sharded(*arrays, 1 << 12, 4, rungs, 0, *clip)
parent_order = r_parent[0].copy()
pid = os.fork()
if pid == 0:
    # child: pool threads are gone; this must complete inline
    r = hn.prep_sharded(*arrays, 1 << 12, 4, rungs, 0, *clip)
    ok = np.array_equal(r[0], parent_order)
    os._exit(0 if ok else 3)
_, status = os.waitpid(pid, 0)
assert os.WIFEXITED(status) and os.WEXITSTATUS(status) == 0, status
print("FORK-OK")
"""
    env = dict(os.environ, GUBER_PREP_THREADS="4", PYTHONPATH=".")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env, timeout=60,
    )
    assert out.returncode == 0, out.stderr[-800:]
    assert "FORK-OK" in out.stdout
