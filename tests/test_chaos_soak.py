"""The chaos soak (scripts/chaos_soak.py) as a test: 3 real daemons,
one SIGKILLed + restarted mid-load, fault injection active, drain under
load — asserting bounded error rate, breaker recovery within 2
cooldowns, zero in-flight loss, and (r11) NO QUOTA AMNESIA: a tracked
over-limit key stays over-limit through owner SIGKILL -> successor
takeover -> restart -> reconcile (GUBER_REPLICATION). Marked `slow`
(tier-1 runs `-m 'not slow'`); the fast deterministic slice of the same
machinery is tests/test_faults.py + tests/test_resilience.py +
tests/test_replication.py. Run it directly with `make chaos` or
`pytest -m slow tests/test_chaos_soak.py`.
"""

import json
import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.slow


def test_chaos_soak_passes(tmp_path):
    out = tmp_path / "chaos.json"
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "chaos_soak.py"),
         "--seconds", "15", "--json", str(out)],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (
        f"chaos soak failed:\n{proc.stdout}\n{proc.stderr}"
    )
    doc = json.loads(out.read_text())
    assert doc["pass"] and not doc["failures"]
    assert doc["error_rate"] < 0.05
    assert doc["inflight_loss"] == 0
    assert doc["recovery_s"] <= doc["recovery_bound_s"] + 1.0
    assert doc["faults_injected"] > 0
    assert doc["counts"]["degraded"] + doc["counts"]["replicated"] > 0
    # the quota-amnesia contract (r11): never under-limit during the
    # outage, over-limit again on the reborn owner, and stable after
    assert doc["amnesia_outage_samples"]["under"] == 0
    assert doc["amnesia_outage_samples"]["over"] > 0
    assert doc["reconcile_lag_s"] is not None
    assert doc["amnesia_reconciled_samples"]["under"] == 0


def test_rolling_deploy_soak_passes(tmp_path):
    """The r17 rolling-deploy soak: 3 etcd-discovered daemons
    (GUBER_RESCALE=1), every node SIGTERMed + restarted in sequence
    under live load — the canary key must answer ZERO under-limit peeks
    through all six membership changes, every drain must exit 0, the
    handoff-lag metric must stay under two flush windows, and the
    rescale counters must prove keys actually moved."""
    out = tmp_path / "rolling.json"
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "chaos_soak.py"),
         "--mode", "rolling", "--seconds", "12", "--json", str(out)],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (
        f"rolling-deploy soak failed:\n{proc.stdout}\n{proc.stderr}"
    )
    doc = json.loads(out.read_text())
    assert doc["pass"] and not doc["failures"]
    assert doc["canary_samples"]["under"] == 0
    assert doc["canary_samples"]["over"] > 30
    assert len(doc["restarts"]) == 3
    assert all(r["drain_exit"] == 0 for r in doc["restarts"])
    assert doc["keys_moved_total"] > 0
    assert doc["handoff_lag_max_s"] <= doc["handoff_lag_bound_s"]
    assert doc["error_rate"] < 0.05


def test_restore_soak_passes(tmp_path):
    """The r19 full-fleet restore soak: 3 daemons checkpointing to
    per-node GUBER_CHECKPOINT_DIR, the WHOLE fleet SIGKILLed at once
    and restarted against the same directories under live load — the
    over-limit canary must answer ZERO under-limit peeks across every
    restore (the first post-restore verdict included), every cycle
    must restore a nonzero number of windows (no silent pass), and the
    restore lag must stay within the staleness bound."""
    out = tmp_path / "restore.json"
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "chaos_soak.py"),
         "--mode", "restore", "--seconds", "12", "--json", str(out)],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (
        f"restore soak failed:\n{proc.stdout}\n{proc.stderr}"
    )
    doc = json.loads(out.read_text())
    assert doc["pass"] and not doc["failures"]
    assert doc["canary_samples"]["under"] == 0
    assert doc["canary_samples"]["over"] > 30
    assert len(doc["cycles"]) >= 1
    for c in doc["cycles"]:
        assert c["restored_windows_total"] > 0
        assert c["restore_lag_s"] is not None
