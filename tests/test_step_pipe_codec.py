"""GMH2 step-pipe codec: round-trip fidelity and hostile-frame rejection.

The step pipe used to frame pickle (GMH1), which made every follower
listen port an arbitrary-code-execution endpoint. GMH2 is a closed-world
TLV codec; these tests pin (a) every message shape the pipe carries
round-trips bit-exactly, and (b) malformed or hostile frames raise
ConnectionError/ValueError instead of constructing anything.
"""

import socket
import struct
import threading

import numpy as np
import pytest

from gubernator_tpu.parallel.multihost import (
    _MAGIC,
    _encode_msg,
    _recv_msg,
    _send_msg,
)


def _roundtrip(msg):
    a, b = socket.socketpair()
    try:
        t = threading.Thread(target=_send_msg, args=(a, msg))
        t.start()
        out = _recv_msg(b)
        t.join()
        return out
    finally:
        a.close()
        b.close()


def _recv_raw(raw: bytes):
    a, b = socket.socketpair()
    try:
        t = threading.Thread(target=a.sendall, args=(raw,))
        t.start()
        try:
            return _recv_msg(b)
        finally:
            t.join()
    finally:
        a.close()
        b.close()


def test_decide_message_roundtrips_bit_exact():
    n = 17
    msg = {
        "kind": "decide",
        "key_hash": np.arange(1, n + 1, dtype=np.uint64) << np.uint64(32),
        "hits": np.ones(n, np.int64),
        "limit": np.full(n, 10, np.int64),
        "duration": np.full(n, 1000, np.int64),
        "algo": np.zeros(n, np.int32),
        "gnp": np.zeros(n, bool),
        "now": 1_700_000_000_123,
    }
    out = _roundtrip(msg)
    assert set(out) == set(msg)
    assert out["kind"] == "decide" and out["now"] == msg["now"]
    for k in ("key_hash", "hits", "limit", "duration", "algo", "gnp"):
        assert out[k].dtype == msg[k].dtype
        np.testing.assert_array_equal(out[k], msg[k])


def test_hello_config_roundtrips_with_tuple_identity():
    # follower compares decoded config to its own with ==; tuples must
    # decode as tuples or every handshake would nack
    cfg = {
        "buckets": (64, 256, 1024, 4096),
        "sub_buckets": (64, 128),
        "store": (16, 4096),
        "n_shards": 8,
    }
    out = _roundtrip({"kind": "hello", "config": cfg})
    assert out["config"] == cfg
    assert isinstance(out["config"]["buckets"], tuple)


def test_none_and_error_string_fields():
    out = _roundtrip({"kind": "sync", "algo": None, "error": "boom ✓"})
    assert out["algo"] is None
    assert out["error"] == "boom ✓"


def test_pickle_frame_is_rejected_not_executed():
    import pickle

    payload = pickle.dumps({"kind": "ack"})
    raw = b"GMH1" + struct.pack("<Q", len(payload)) + payload
    with pytest.raises(ConnectionError):
        _recv_raw(raw)


def test_unknown_tag_rejected():
    body = bytes([250])
    raw = _MAGIC + struct.pack("<Q", len(body)) + body
    with pytest.raises(ConnectionError):
        _recv_raw(raw)


def test_unknown_dtype_rejected():
    # dict(1 entry) -> key "x" -> array tag with dtype code 9
    body = bytearray([5]) + struct.pack("<I", 1)
    body += struct.pack("<H", 1) + b"x"
    body += bytes([3, 9, 1]) + struct.pack("<I", 4)
    raw = _MAGIC + struct.pack("<Q", len(bytes(body))) + bytes(body)
    with pytest.raises(ConnectionError):
        _recv_raw(raw)


def test_truncated_array_rejected():
    msg = {"kind": "decide", "key_hash": np.arange(8, dtype=np.uint64)}
    raw = _encode_msg(msg)[:-3]
    # honest length header, short body: reader hits EOF
    a, b = socket.socketpair()
    try:
        a.sendall(raw)
        a.close()
        with pytest.raises(ConnectionError):
            _recv_msg(b)
    finally:
        b.close()


def test_length_lie_trailing_bytes_rejected():
    body = bytearray()
    from gubernator_tpu.parallel.multihost import _encode_value

    _encode_value(body, {"kind": "ack"})
    body += b"XX"  # valid message followed by junk inside the frame
    raw = _MAGIC + struct.pack("<Q", len(bytes(body))) + bytes(body)
    with pytest.raises(ConnectionError):
        _recv_raw(raw)


def test_non_whitelisted_type_refuses_to_encode():
    with pytest.raises(ValueError):
        _encode_msg({"kind": "decide", "f": 1.5})
    with pytest.raises(ValueError):
        _encode_msg({"kind": "decide", "arr": np.zeros(4, np.float32)})


def test_invalid_utf8_rejected_as_connection_error():
    # hostile bytes in a string field must stay inside the codec's
    # declared error contract, not leak UnicodeDecodeError
    body = bytearray([5]) + struct.pack("<I", 1)
    body += struct.pack("<H", 1) + b"\xff"  # dict key is invalid utf-8
    body += bytes([0])  # value: None
    raw = _MAGIC + struct.pack("<Q", len(bytes(body))) + bytes(body)
    with pytest.raises(ConnectionError):
        _recv_raw(raw)
    # and in a string value
    body2 = bytearray([5]) + struct.pack("<I", 1)
    body2 += struct.pack("<H", 1) + b"k"
    body2 += bytes([2]) + struct.pack("<I", 2) + b"\xc3\x28"
    raw2 = _MAGIC + struct.pack("<Q", len(bytes(body2))) + bytes(body2)
    with pytest.raises(ConnectionError):
        _recv_raw(raw2)
