"""Hostile-input fuzz for the edge's hand-rolled HTTP/2 + gRPC layer.

The gRPC door (native/edge/h2_grpc.inc) parses h2 frames, HPACK (with a
dynamic table and Huffman), and protobuf by hand — every byte of it
attacker-reachable before any request validation. Mirrors the HTTP
door's fuzz (test_edge_fuzz.py): after EVERY hostile input the edge
must still be alive and answer a well-formed gRPC request on a fresh
connection — no crash, no wedge, no desync.
"""

import os
import pathlib
import socket
import struct
import subprocess
import sys
import threading
import time

import grpc
import pytest

from gubernator_tpu.api.proto.gen import gubernator_pb2
from gubernator_tpu.api.grpc_glue import V1Stub
from gubernator_tpu.api.types import RateLimitResp, Status
from gubernator_tpu.serve.edge_bridge import EdgeBridge

from tests._util import edge_binary

EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

PORT = 19585
GRPC_PORT = 19586
SOCK = "/tmp/guber-edge-grpc-fuzz.sock"

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


class FakeInstance:
    async def get_rate_limits(self, reqs, stage_frame=False):
        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=r.limit - r.hits, reset_time=1700000000000,
            )
            for r in reqs
        ]


@pytest.fixture(scope="module")
def edge():
    import asyncio

    pathlib.Path(SOCK).unlink(missing_ok=True)
    loop = asyncio.new_event_loop()
    bridge = EdgeBridge(FakeInstance(), SOCK)

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(bridge.start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    for _ in range(50):
        if pathlib.Path(SOCK).exists():
            break
        time.sleep(0.05)
    proc = subprocess.Popen(
        [str(EDGE_BIN), "--listen", str(PORT), "--grpc-listen",
         str(GRPC_PORT), "--backend", SOCK, "--batch-wait-us", "200",
         "--recv-timeout-s", "1"],
        stdout=sys.stderr, stderr=subprocess.STDOUT,
    )
    for _ in range(100):
        try:
            with socket.create_connection(("127.0.0.1", GRPC_PORT), 0.2):
                break
        except OSError:
            time.sleep(0.05)
    else:
        proc.kill()
        raise RuntimeError("edge did not listen")
    yield proc
    proc.terminate()
    proc.wait(timeout=5)

    async def shutdown():
        await bridge.stop()
        loop.stop()

    loop.call_soon_threadsafe(lambda: loop.create_task(shutdown()))
    t.join(timeout=5)


def _frame(ftype, flags, sid, payload=b""):
    n = len(payload)
    return (
        bytes([(n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF, ftype, flags])
        + struct.pack(">I", sid & 0x7FFFFFFF)
        + payload
    )


def _send_raw(data: bytes, linger: float = 0.3):
    """Fire hostile bytes at the gRPC port; drain whatever comes back."""
    try:
        with socket.create_connection(("127.0.0.1", GRPC_PORT), 3) as s:
            s.settimeout(linger)
            s.sendall(data)
            try:
                while s.recv(65536):
                    pass
            except (socket.timeout, OSError):
                pass
    except OSError:
        pass  # edge may slam the door — that's a legal response


def _assert_alive(edge):
    """The only invariant that matters: a well-formed request still
    round-trips after the garbage."""
    assert edge.poll() is None, "edge process died"
    chan = grpc.insecure_channel(f"127.0.0.1:{GRPC_PORT}")
    try:
        r = V1Stub(chan).GetRateLimits(
            gubernator_pb2.GetRateLimitsReq(
                requests=[
                    gubernator_pb2.RateLimitReq(
                        name="fz", unique_key="ok", hits=1, limit=9,
                        duration=60_000,
                    )
                ]
            ),
            timeout=10,
        )
        assert r.responses[0].limit == 9
    finally:
        chan.close()


CORPUS = [
    # not h2 at all
    b"GET / HTTP/1.1\r\nHost: x\r\n\r\n",
    b"\x00" * 64,
    os.urandom(256),
    # valid preface, then garbage frames
    PREFACE + os.urandom(128),
    # preface + oversized frame length header
    PREFACE + bytes([0xFF, 0xFF, 0xFF, 0x00, 0x00, 0, 0, 0, 0]),
    # preface + SETTINGS with a bogus (non-multiple-of-6) length
    PREFACE + _frame(0x4, 0, 0, b"\x00\x01\x02"),
    # HEADERS on stream 0 (protocol error)
    PREFACE + _frame(0x4, 0, 0) + _frame(0x1, 0x4, 0, b"\x82"),
    # HEADERS on an even (server) stream id
    PREFACE + _frame(0x4, 0, 0) + _frame(0x1, 0x4, 2, b"\x82"),
    # HEADERS with hostile HPACK: indexed entry far past both tables
    PREFACE + _frame(0x4, 0, 0) + _frame(0x1, 0x5, 1, b"\xff\xff\xff\x7f"),
    # HPACK literal with huge declared string length
    PREFACE + _frame(0x4, 0, 0)
    + _frame(0x1, 0x5, 1, b"\x00\x7f\xff\xff\xff\x7f"),
    # HPACK Huffman string with invalid padding (all-zero bits)
    PREFACE + _frame(0x4, 0, 0)
    + _frame(0x1, 0x5, 1, b"\x00\x01a\x81\x00"),
    # DATA for a stream that was never opened
    PREFACE + _frame(0x4, 0, 0) + _frame(0x0, 0x1, 7, b"hello"),
    # CONTINUATION without a preceding HEADERS
    PREFACE + _frame(0x4, 0, 0) + _frame(0x9, 0x4, 1, b"\x82"),
    # WINDOW_UPDATE with a bad length
    PREFACE + _frame(0x4, 0, 0) + _frame(0x8, 0, 0, b"\x00\x00"),
    # PING with wrong payload size
    PREFACE + _frame(0x4, 0, 0) + _frame(0x6, 0, 0, b"\x01\x02"),
    # RST_STREAM spam for random streams
    PREFACE + _frame(0x4, 0, 0)
    + b"".join(_frame(0x3, 0, i, b"\x00\x00\x00\x00") for i in
               range(1, 64, 2)),
    # truncated frame header (connection cut mid-header)
    PREFACE + b"\x00\x00",
    # a valid-looking HEADERS then DATA with a lying gRPC length prefix
    PREFACE + _frame(0x4, 0, 0)
    + _frame(0x1, 0x4, 1, b"\x82")  # :method GET, no END_HEADERS needed
    + _frame(0x0, 0x1, 1, b"\x00\xff\xff\xff\xff"),
    # unknown frame types must be ignored per spec
    PREFACE + _frame(0x4, 0, 0) + _frame(0xEE, 0xFF, 3, b"junk")
    + _frame(0x6, 0, 0, b"12345678"),
]


def test_hostile_inputs_never_kill_the_edge(edge):
    for i, blob in enumerate(CORPUS):
        _send_raw(blob)
    _assert_alive(edge)


def test_slow_preface_times_out_and_edge_survives(edge):
    try:
        with socket.create_connection(("127.0.0.1", GRPC_PORT), 3) as s:
            s.sendall(PREFACE[:10])  # stall mid-preface
            time.sleep(1.5)  # > --recv-timeout-s
            s.settimeout(0.5)
            try:
                s.recv(16)
            except (socket.timeout, OSError):
                pass
    except OSError:
        pass
    _assert_alive(edge)


def test_window_update_flood_bounded(edge):
    """WINDOW_UPDATEs for thousands of fictitious streams must not grow
    unbounded state (stream_window cap) or wedge the connection."""
    blob = PREFACE + _frame(0x4, 0, 0) + b"".join(
        _frame(0x8, 0, sid, struct.pack(">I", 1))
        for sid in range(1, 12000, 2)
    )
    _send_raw(blob, linger=0.5)
    _assert_alive(edge)


def test_interleaved_garbage_then_real_traffic_same_port(edge):
    """Alternate hostile connections with real ones: state from a
    poisoned connection must never leak into a healthy one."""
    for blob in CORPUS[::3]:
        _send_raw(blob)
        _assert_alive(edge)


# ---------------------------------------------------------------------------
# Windowed (GEB2/GEB7) framing fuzz — r7. Two directions: hostile
# windowed frames INTO the bridge's socket (a desynced or malicious
# edge), and a hostile BRIDGE feeding garbage windowed responses to the
# edge's reader thread (the only place the edge parses frames it did
# not originate). test_edge_asan.py re-runs this module against the
# sanitized binary, so both sides of the new framing get ASan coverage.
# ---------------------------------------------------------------------------

from gubernator_tpu.serve.edge_bridge import (  # noqa: E402
    HELLO_FAST,
    HELLO_WINDOWED,
    MAGIC_HELLO,
    MAGIC_STALE,
    MAGIC_WFAST_REQ,
    MAGIC_WFAST_RESP,
    MAGIC_WREQ,
    MAGIC_WRESP,
    ring_fingerprint,
)


def _witems(n):
    item = (
        struct.pack("<H", 3) + b"api"
        + struct.pack("<H", 1) + b"k"
        + struct.pack("<qqqBB", 1, 5, 1000, 0, 0)
    )
    return item * n


WINDOWED_BRIDGE_CORPUS = [
    # GEB2 whose payload length disagrees with the item encoding
    struct.pack("<II", MAGIC_WREQ, 3)
    + struct.pack("<IQ", 1, 0) + struct.pack("<I", 4) + b"\xff" * 4,
    # GEB2 header then EOF (cut mid-frame)
    struct.pack("<II", MAGIC_WREQ, 5) + struct.pack("<IQ", 2, 0),
    # GEB7 with a payload that is not n x 33 bytes
    struct.pack("<II", MAGIC_WFAST_REQ, 4)
    + struct.pack("<IIQ", 3, 0, 0) + struct.pack("<I", 7) + b"\x00" * 7,
    # absurd item count with a tiny payload
    struct.pack("<II", MAGIC_WREQ, 1 << 30)
    + struct.pack("<IQ", 4, 0) + struct.pack("<I", 2) + b"ab",
    # GEBR sent TO the bridge (only the bridge may send it)
    struct.pack("<II", MAGIC_STALE, 9),
    # stamp from the far future (transit attribution must drop it)
    struct.pack("<II", MAGIC_WREQ, 1)
    + struct.pack("<IQ", 5, 1 << 62)
    + struct.pack("<I", len(_witems(1))) + _witems(1),
]


def test_hostile_windowed_frames_at_bridge_socket(edge):
    """Garbage GEB2/GEB7 frames straight into the bridge's unix socket:
    each hostile connection may die, but the bridge (and the edge's
    gRPC door riding it) must keep serving."""
    for blob in WINDOWED_BRIDGE_CORPUS:
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(2)
            s.connect(SOCK)
            s.recv(65536)  # hello
            s.sendall(blob)
            try:
                while s.recv(65536):
                    pass
            except (socket.timeout, OSError):
                pass
            s.close()
        except OSError:
            pass
        _assert_alive(edge)


HOSTILE_PORT = 19591
HOSTILE_SOCK = "/tmp/guber-edge-hostile-bridge.sock"


def test_windowed_hostile_bridge_responses_fail_cleanly():
    """A hostile bridge answers the edge's windowed frames with garbage
    — unknown magic, unknown frame id, absurd record count, GEBR, a
    truncated header — one per connection. The edge must fail each
    in-flight batch cleanly (503 / per-item retry errors), reconnect,
    and once the bridge behaves, serve a real decision. This is the
    reader-thread parse surface, the only frames the edge did not
    originate."""
    import asyncio
    import json as _json
    import queue as _queue
    import urllib.request
    import urllib.error

    hostile = _queue.Queue()
    for mode in ("bad_magic", "unknown_fid", "absurd_count", "gebr",
                 "truncate"):
        hostile.put(mode)

    grpc_addr = "127.0.0.1:9991"
    rhash = ring_fingerprint([grpc_addr])

    def hello():
        flags = HELLO_FAST | HELLO_WINDOWED | (4 << 16)
        g = grpc_addr.encode()
        return (
            struct.pack("<IIII", MAGIC_HELLO, flags, rhash, 1)
            + struct.pack("<BH", 1, len(g)) + g + struct.pack("<H", 0)
        )

    async def handle(reader, writer):
        try:
            writer.write(hello())
            await writer.drain()
            while True:
                hdr = await reader.readexactly(8)
                magic, n = struct.unpack("<II", hdr)
                if magic == MAGIC_WFAST_REQ:
                    fid, _rh, _ts = struct.unpack(
                        "<IIQ", await reader.readexactly(16)
                    )
                elif magic == MAGIC_WREQ:
                    fid, _ts = struct.unpack(
                        "<IQ", await reader.readexactly(12)
                    )
                else:
                    return  # non-windowed frame: not this bridge's deal
                (plen,) = struct.unpack(
                    "<I", await reader.readexactly(4)
                )
                await reader.readexactly(plen)
                try:
                    mode = hostile.get_nowait()
                except _queue.Empty:
                    mode = "behave"
                if mode == "bad_magic":
                    writer.write(struct.pack("<II", 0xDEADBEEF, 0))
                    await writer.drain()
                    return
                if mode == "unknown_fid":
                    writer.write(
                        struct.pack("<II", MAGIC_WFAST_RESP, n)
                        + struct.pack("<I", fid ^ 0x5A5A)
                        + b"\x00" * (25 * n)
                    )
                    await writer.drain()
                    return
                if mode == "absurd_count":
                    writer.write(
                        struct.pack("<II", MAGIC_WFAST_RESP, 1 << 28)
                        + struct.pack("<I", fid)
                    )
                    await writer.drain()
                    return
                if mode == "gebr":
                    writer.write(struct.pack("<II", MAGIC_STALE, fid))
                    await writer.drain()
                    return
                if mode == "truncate":
                    writer.write(struct.pack("<II", MAGIC_WFAST_RESP, n))
                    await writer.drain()
                    return
                # behave: well-formed windowed response, every item OK
                rec = struct.pack("<Bqqq", 0, 9, 8, 1)
                if magic == MAGIC_WFAST_REQ:
                    writer.write(
                        struct.pack("<II", MAGIC_WFAST_RESP, n)
                        + struct.pack("<I", fid) + rec * n
                    )
                else:
                    item = rec + struct.pack("<H", 0) + struct.pack("<H", 0)
                    writer.write(
                        struct.pack("<II", MAGIC_WRESP, n)
                        + struct.pack("<I", fid) + item * n
                    )
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    pathlib.Path(HOSTILE_SOCK).unlink(missing_ok=True)
    loop = asyncio.new_event_loop()
    server_box = {}

    def run_loop():
        asyncio.set_event_loop(loop)
        server_box["srv"] = loop.run_until_complete(
            asyncio.start_unix_server(handle, HOSTILE_SOCK)
        )
        loop.run_forever()

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    for _ in range(100):
        if pathlib.Path(HOSTILE_SOCK).exists():
            break
        time.sleep(0.05)

    proc = subprocess.Popen(
        [str(EDGE_BIN), "--listen", str(HOSTILE_PORT), "--backend",
         HOSTILE_SOCK, "--workers", "1", "--batch-wait-us", "100"],
        stdout=sys.stderr, stderr=subprocess.STDOUT,
    )
    try:
        for _ in range(100):
            try:
                with socket.create_connection(
                    ("127.0.0.1", HOSTILE_PORT), 0.2
                ):
                    break
            except OSError:
                time.sleep(0.05)
        else:
            raise RuntimeError("edge did not listen")

        body = _json.dumps(
            {"requests": [{"name": "fz", "uniqueKey": "ok", "hits": 1,
                           "limit": 9, "duration": 60000}]}
        ).encode()
        url = f"http://127.0.0.1:{HOSTILE_PORT}/v1/GetRateLimits"
        deadline = time.monotonic() + 30
        ok = False
        while time.monotonic() < deadline:
            assert proc.poll() is None, "edge died on hostile response"
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = _json.loads(
                    urllib.request.urlopen(req, timeout=5).read()
                )
                r0 = resp["responses"][0]
                if not r0.get("error") and int(r0.get("limit", 0)) == 9:
                    ok = True
                    break
            except (urllib.error.HTTPError, urllib.error.URLError,
                    OSError):
                pass  # hostile phase: 503s / resets are the contract
            time.sleep(0.1)
        assert ok, "edge never recovered after the bridge became sane"
        assert hostile.empty(), "not every hostile mode was exercised"
        assert proc.poll() is None
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        pathlib.Path(HOSTILE_SOCK).unlink(missing_ok=True)
