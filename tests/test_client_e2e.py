"""End-to-end test through a subprocess cluster.

The analogue of the reference's Python client e2e fixture
(reference python/tests/test_client.py): launch the standalone cluster
entry point as a subprocess, wait for "Ready" on stdout, then exercise
health checks and rate limits over real sockets from a different process.
"""

import os
import subprocess
import sys
import time

import pytest

from gubernator_tpu.api.types import Algorithm, RateLimitReq, Status, SECOND
from gubernator_tpu.client import V1Client


@pytest.fixture(scope="module")
def cluster_proc():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.cluster_main"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    # Read "Ready" under a hard deadline: readline() itself can block
    # forever if the process wedges without output, so it runs on a
    # daemon thread and the main thread enforces the timeout.
    import queue
    import threading

    lines: "queue.Queue[str]" = queue.Queue()

    def _pump():
        for ln in proc.stdout:
            lines.put(ln)

    threading.Thread(target=_pump, daemon=True).start()
    deadline = time.monotonic() + 60
    while True:
        if proc.poll() is not None:
            pytest.fail(f"cluster process died (rc={proc.returncode})")
        try:
            if "Ready" in lines.get(timeout=1):
                break
        except queue.Empty:
            pass
        if time.monotonic() > deadline:
            proc.kill()
            pytest.fail("cluster did not print Ready in time")
    yield proc
    proc.terminate()
    proc.wait(timeout=10)


def test_health_check(cluster_proc):
    with V1Client("127.0.0.1:9090") as client:
        h = client.health_check(timeout=5)
    assert h.status == "healthy"
    assert h.peer_count == 6


def test_get_rate_limit(cluster_proc):
    with V1Client("127.0.0.1:9091") as client:
        reqs = [
            RateLimitReq(
                name="test_e2e",
                unique_key="account:1234",
                algorithm=Algorithm.TOKEN_BUCKET,
                duration=SECOND * 2,
                limit=10,
                hits=1,
            )
        ]
        rl = client.get_rate_limits(reqs, timeout=10)[0]
        assert rl.error == ""
        assert rl.status == Status.UNDER_LIMIT
        assert rl.remaining == 9
        rl = client.get_rate_limits(reqs, timeout=10)[0]
        assert rl.remaining == 8
