"""Sketch cold tier (r13/r21): identity, fail-closed error, promotion.

The two-tier contract under test (core/kernels.decide_presorted_sketch,
core/sketches.py, serve/promoter.py):

- exact-tier keys are BYTE-IDENTICAL with the tier on vs off — the
  sketch only changes the fate of creates the exact store DROPS to way
  exhaustion, and store contents evolve identically either way (the
  writeback plan is sketch-independent), so with no drop pressure the
  two pipelines are indistinguishable end to end for ALL FOUR
  algorithms (differential fuzz, exact-capacity stores, device
  tpu-on-cpu pipeline, r10 fake clock);
- under pressure, every divergent row is AT-LEAST-AS-RESTRICTIVE with
  the tier on (status >=, remaining <=): sketch estimates never
  under-count the hits they were charged with, so the error is
  one-sided — fail-closed, matching the shed cache's stance. Since
  r21 this covers sliding (window-ring blend) and GCRA (TAT-quantized
  reconstruction), each pinned bit-exact against its host twin
  (algorithms.sketch_sliding_budget / sketch_gcra_budget) on
  pinned-bucket single-key drives across rotations and clock jumps.
  NOTE the scoping: strict per-request dominance vs the EXACT oracle
  is impossible once refusal histories diverge (an early sketch
  refusal leaves budget an exact path would have consumed), so the
  row-wise property is asserted ON-vs-OFF — the OFF engine serves
  dropped creates as phantom-fresh windows, strictly more permissive;
- the measured tail error on a pinned zipf stream stays within the
  documented e*N/width bound with ZERO under-counts, and the v2
  derivation (saturating int32 counters, core/sketches.py) yields a
  strictly tighter bound than r13 at the same byte budget (the
  property the BENCH_SKETCH_r21.json acceptance commits);
- device and host sketch indexing are bit-identical twins;
- promotion migrates the estimate into an exact bucket (the window
  continues, then the key decides exactly), never clobbers live exact
  state, and feeds over-limit candidates to the shed cache.
"""

import asyncio

import numpy as np
import pytest

import gubernator_tpu.core  # noqa: F401  (x64)
from gubernator_tpu.api.types import (
    Algorithm,
    PeerInfo,
    RateLimitReq,
    Status,
)
from gubernator_tpu.core.engine import TpuEngine
from gubernator_tpu.core.sketches import (
    SketchConfig,
    derive_sketch_config,
    new_sketch,
    sketch_footprint_bytes,
    sketch_indices_np,
    window_id_np,
)
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve.backends import TpuBackend
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import Instance
from gubernator_tpu.serve.shedcache import ShedCache

T0 = 1_700_000_000_000
ADDR = "127.0.0.1:7973"


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self) -> int:
        return self.t


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


def _pressure_engine(sketch=True, width=1 << 12):
    """1-way 16-bucket store: trivial to saturate, so drops flow."""
    return TpuEngine(
        StoreConfig(rows=1, slots=16),
        buckets=(64, 256),
        sketch=SketchConfig(rows=4, width=width) if sketch else None,
    )


def _keys(n, tag=7):
    # distinct fingerprints (id << 32) spread over buckets
    return (np.arange(1, n + 1, dtype=np.uint64) << np.uint64(32)) | (
        np.uint64(tag)
    )


def _pin_buckets(eng, nf=16):
    """Fill EVERY bucket's single way with an immortal filler (one key
    per bucket, cli/bench_serving._filler_hashes) and return the filler
    hashes: included in each later batch as peeks, they are found-
    writers, so a rank-0 miss can never evict them — every measured
    key provably decides on the sketch tier."""
    from gubernator_tpu.cli.bench_serving import _filler_hashes

    fillers = _filler_hashes(eng.config.slots)
    ones = np.ones(fillers.shape[0], np.int64)
    eng.decide_arrays(
        fillers, ones, ones * 1000, ones * 1_000_000_000,
        np.zeros(fillers.shape[0], np.int32),
        np.zeros(fillers.shape[0], bool), T0,
    )
    return fillers


# -- config / sizing --------------------------------------------------------


def test_sketch_config_and_derivation():
    # r13 derivation: 4 rows of int64 — the committed r13 geometry
    c = derive_sketch_config(mib=16, rows=4, derivation="r13")
    assert c.width == 1 << 19 and c.counter_bytes == 8
    assert sketch_footprint_bytes(c) == 16 << 20
    assert derive_sketch_config(mib=8, derivation="r13").width == 1 << 18
    # v2 derivation (default): 2 rows of saturating int32 — 4x the
    # width at the same budget, so a 4x tighter additive error bound
    v = derive_sketch_config(mib=8)
    assert v.rows == 2 and v.counter_bytes == 4
    assert v.width == 1 << 20
    assert sketch_footprint_bytes(v) == 8 << 20
    # explicit rows keep the derivation's counter dtype
    assert derive_sketch_config(mib=16, rows=4).width == 1 << 20
    # direct construction stays r13-compatible (int64 default)
    assert SketchConfig(rows=4, width=1 << 12).counter_bytes == 8
    import jax.numpy as jnp

    assert new_sketch(v).data.dtype == jnp.int32
    assert new_sketch(c).data.dtype == jnp.int64
    with pytest.raises(AssertionError):
        SketchConfig(rows=4, width=1000)  # not a power of two
    with pytest.raises(AssertionError):
        SketchConfig(rows=9, width=1 << 10)  # more rows than salts
    with pytest.raises(AssertionError):
        SketchConfig(rows=2, width=1 << 10, counter_bytes=2)
    with pytest.raises(ValueError):
        derive_sketch_config(mib=0)
    with pytest.raises(ValueError):
        derive_sketch_config(mib=8, derivation="r12")


def test_store_mib_carve_out_and_host_budget():
    """GUBER_STORE_MIB covers BOTH tiers: the exact tier shrinks by the
    sketch's resolved footprint; an impossible split fails loudly; the
    whole-host lint flags shed/standby overflow."""
    from gubernator_tpu.core.store import (
        check_host_budget,
        check_store_budget,
        store_footprint_bytes,
    )

    full = ServerConfig(
        backend="tpu", store_mib=1024, sketch=False
    ).store_config()
    carved = ServerConfig(
        backend="tpu", store_mib=1024, sketch=True, sketch_mib=256
    ).store_config()
    assert store_footprint_bytes(carved) <= (1024 - 256) << 20
    assert store_footprint_bytes(carved) < store_footprint_bytes(full)
    # mesh carries the sharded sketch since r14: same carve-out as tpu;
    # multihost joins in r20 (promotion + estimate reads are lockstep
    # collectives), so its budget carves identically too
    mesh = ServerConfig(
        backend="mesh", store_mib=1024, sketch=True, sketch_mib=256
    ).store_config()
    assert store_footprint_bytes(mesh) == store_footprint_bytes(carved)
    mh = ServerConfig(
        backend="multihost", store_mib=1024, sketch=True, sketch_mib=256
    ).store_config()
    assert store_footprint_bytes(mh) == store_footprint_bytes(carved)
    with pytest.raises(ValueError):
        ServerConfig(
            backend="tpu", store_mib=16, sketch=True, sketch_mib=16
        ).store_config()
    # tiny budget + AUTO sketch: the tier auto-disables (pre-r13 tiny
    # configs keep booting); the hard refusal is reserved for an
    # EXPLICIT GUBER_SKETCH_MIB (review finding)
    tiny = ServerConfig(backend="tpu", store_mib=1, sketch=True)
    assert tiny.sketch_config() is None
    assert store_footprint_bytes(tiny.store_config()) == 1 << 20
    with pytest.raises(ValueError):
        ServerConfig(
            backend="tpu", store_mib=1, sketch=True, sketch_mib=1
        ).store_config()
    # cold_tier suppresses the undersize lint (tail overflow is the
    # sketch's job) but keeps the oversize lint
    small = ServerConfig(backend="tpu", store_mib=64, sketch=False)
    sc = small.store_config()
    assert check_store_budget(sc, 100_000_000) != ""
    assert check_store_budget(sc, 100_000_000, cold_tier=True) == ""
    assert check_store_budget(sc, 1000, cold_tier=True) != ""  # oversize
    # whole-host budget: parts must fit the declared MiB
    assert check_host_budget(1, {"a": 2 << 20}) != ""
    assert check_host_budget(4, {"a": 2 << 20, "b": 1 << 20}) == ""
    assert check_host_budget(0, {"a": 1 << 30}) == ""  # no budget


def test_install_windows_chunks_past_ladder_top():
    """A promotion batch larger than the bucket ladder's top rung is
    chunked, not refused — GUBER_SKETCH_TOPK has no relation to the
    ladder, and a choose_bucket refusal would wedge every promotion
    tick (review finding)."""
    eng = TpuEngine(
        StoreConfig(rows=16, slots=1 << 8), buckets=(64,),
        sketch=SketchConfig(rows=4, width=1 << 12),
    )
    n = 150  # > ladder top 64
    kh = _keys(n)
    eng.install_windows(
        kh, np.full(n, 10, np.int64), np.full(n, 5, np.int64),
        np.full(n, T0 + 60_000, np.int64), np.zeros(n, bool), T0,
    )
    assert eng.live_mask(kh, T0 + 1).all()


def test_host_budget_strict_gates_on_explicit_host_knobs(caplog):
    """STRICT + tiny budget + DEFAULT shed cache must still boot (the
    default shed alone overflows small budgets — failing would regress
    every pre-r13 strict config); an EXPLICITLY oversized host part
    under STRICT refuses (review finding)."""
    import logging

    from gubernator_tpu.serve.server import make_backend

    conf = ServerConfig(
        backend="tpu", store_mib=16, store_size_strict=True,
        device_batch_limit=1000,
    )
    with caplog.at_level(logging.WARNING):
        make_backend(conf)  # boots; the lint only warns
    assert any("exceeded" in r.message for r in caplog.records)
    with pytest.raises(ValueError, match="STRICT"):
        make_backend(
            ServerConfig(
                backend="tpu", store_mib=16, store_size_strict=True,
                shed_cache_keys=1_000_000, device_batch_limit=1000,
            )
        )


def test_sketch_knob_validation():
    # 0 rows = derivation default since r21 (v2: 2, r13: 4)
    ServerConfig(sketch_rows=0).validate()
    skc = ServerConfig(backend="tpu", sketch_rows=0).sketch_config()
    assert skc.rows == 2 and skc.counter_bytes == 4
    r13 = ServerConfig(
        backend="tpu", sketch_derivation="r13"
    ).sketch_config()
    assert r13.rows == 4 and r13.counter_bytes == 8
    with pytest.raises(ValueError):
        ServerConfig(sketch_rows=-1).validate()
    with pytest.raises(ValueError):
        ServerConfig(sketch_rows=9).validate()
    with pytest.raises(ValueError):
        ServerConfig(sketch_derivation="r12").validate()
    with pytest.raises(ValueError):
        ServerConfig(sketch_mib=-1).validate()
    with pytest.raises(ValueError):
        ServerConfig(sketch_topk=0).validate()


def test_algo_registry_pins():
    """The r21 registry audit: every eligibility gate derives from
    core/algorithms.ALGORITHMS, and the three gates intentionally
    DIFFER — widening one without auditing its consumers must fail
    here, loudly, instead of silently shipping the r15 assumption
    (sketch tier == token/leaky only) into a consumer.

    - SKETCH_SERVABLE: all four. The window-ring (r21) reconstructs
      sliding and GCRA budgets one-sidedly from per-window counts.
    - PROMOTABLE: token ONLY. install_windows fabricates the token
      fixed-window layout; promoting a sliding/GCRA key would reset
      its phase and under-restrict. Ring keys are served, not promoted.
    - SHEDDABLE: token ONLY. The shed cache freezes an OVER verdict to
      the window end; sliding/GCRA budgets refill continuously, so a
      frozen verdict would over-restrict for up to a full window —
      serve them from the sketch tier instead (fail-closed but live).
    """
    from gubernator_tpu.core.algorithms import (
        ALGO_TOKEN,
        PROMOTABLE_ALGOS,
        SHEDDABLE_ALGOS,
        SKETCH_SERVABLE_ALGOS,
    )

    assert SKETCH_SERVABLE_ALGOS == {0, 1, 2, 3}
    assert PROMOTABLE_ALGOS == {ALGO_TOKEN}
    assert SHEDDABLE_ALGOS == {ALGO_TOKEN}
    # the gates are registry-derived, not parallel hand-written sets
    from gubernator_tpu.core.algorithms import ALGORITHMS

    assert SKETCH_SERVABLE_ALGOS == {
        a for a, s in ALGORITHMS.items() if s.sketch_servable
    }
    assert SHEDDABLE_ALGOS == {
        a for a, s in ALGORITHMS.items() if s.sheddable
    }
    # consumers import the gates (grep-level pin: shedcache asserts at
    # import time, promoter builds its mask from PROMOTABLE_ALGOS)
    from gubernator_tpu.serve import promoter as promoter_mod

    assert set(promoter_mod._PROMOTABLE_IDS.tolist()) == PROMOTABLE_ALGOS


# -- indexing twins ---------------------------------------------------------


def test_device_host_index_twins():
    """The kernel's conservative update lands counts at EXACTLY the
    host-computed (row, index) positions: read the raw sketch array at
    sketch_indices_np positions and recover every charged count."""
    eng = _pressure_engine()
    n = 48
    kh = _keys(n)
    ones = np.ones(n, np.int64)
    dur = np.full(n, 10_000, np.int64)
    eng.decide_arrays(
        kh, ones, ones * 100, dur, np.zeros(n, np.int32),
        np.zeros(n, bool), T0,
    )
    dropped = eng.stats.snapshot()["dropped"]
    assert dropped > 0
    e_now = int(eng.clock.to_engine(T0))
    idx = sketch_indices_np(
        kh, window_id_np(e_now, dur), eng.sketch_config
    )
    data = np.asarray(eng.sketch.data)
    per_row = np.stack(
        [data[r, idx[r]] for r in range(idx.shape[0])]
    )
    est_host = per_row.min(axis=0)
    est_engine = eng.sketch_estimates(kh, dur, T0 + 1)
    np.testing.assert_array_equal(est_host, est_engine)
    # exactly the dropped keys carry charge 1, the rest 0
    assert int((est_engine == 1).sum()) == dropped
    assert int((est_engine == 0).sum()) == n - dropped


# -- tier semantics ---------------------------------------------------------


def test_sketch_tier_fixed_window_semantics():
    """A sketch-served key follows fixed-window token math: budget
    drains across batches, freezes OVER at the limit with reset = the
    window's end, and the next window starts fresh."""
    eng = _pressure_engine()
    fillers = _pin_buckets(eng)
    nf = fillers.shape[0]
    # one measured key + the fillers in every batch (found-writers
    # block rank-0 eviction, so the key always drops to the sketch)
    key = _keys(1, tag=9)[:1]
    DUR, LIM = 10_000, 3
    for i in range(5):
        kh = np.concatenate([fillers, key])
        hits = np.concatenate([np.zeros(nf, np.int64), [1]])
        s, l, r, t = eng.decide_arrays(
            kh, hits, np.full(nf + 1, LIM, np.int64),
            np.full(nf + 1, DUR, np.int64),
            np.zeros(nf + 1, np.int32), np.zeros(nf + 1, bool),
            T0 + i,
        )
        # engine-ms: the epoch pins ONE ms before first contact (r15,
        # core/engine.py EpochClock — engine 0 is the wire's no-reset
        # sentinel), so the fixed-window grid anchors at T0 - 1
        e_now = (T0 + i) - (T0 - 1)
        window_end_unix = (T0 - 1) + ((e_now // DUR) + 1) * DUR
        if i < LIM:
            assert s[-1] == int(Status.UNDER_LIMIT)
            assert r[-1] == LIM - (i + 1)
        else:
            assert s[-1] == int(Status.OVER_LIMIT)
            assert r[-1] == 0
        assert t[-1] == window_end_unix
    # cross the window boundary: fresh budget
    t_next = T0 + DUR + 1
    kh = np.concatenate([fillers, key])
    hits = np.concatenate([np.zeros(nf, np.int64), [1]])
    s, l, r, t = eng.decide_arrays(
        kh, hits, np.full(nf + 1, LIM, np.int64),
        np.full(nf + 1, DUR, np.int64), np.zeros(nf + 1, np.int32),
        np.zeros(nf + 1, bool), t_next,
    )
    assert s[-1] == int(Status.UNDER_LIMIT) and r[-1] == LIM - 1


def test_reset_and_rebase_clear_sketch():
    eng = _pressure_engine()
    n = 48
    kh = _keys(n)
    ones = np.ones(n, np.int64)
    dur = np.full(n, 10_000, np.int64)
    eng.decide_arrays(
        kh, ones, ones * 100, dur, np.zeros(n, np.int32),
        np.zeros(n, bool), T0,
    )
    assert int(eng.sketch_estimates(kh, dur, T0 + 1).sum()) > 0
    eng.reset()
    assert int(np.asarray(eng.sketch.data).sum()) == 0


# -- differential identity --------------------------------------------------


def _twin_arrays(seed, slots, rows, steps=60, keyspace=24,
                 hit_pool=(0, 1, 1, 2), limit_pool=(5, 8, 50),
                 dur_pool=(400, 2000, 60_000),
                 dt_pool=(0, 1, 7, 500, 2500), token_only=False,
                 algo_pool=None):
    """Drive identical random array batches through sketch-ON and
    sketch-OFF engines; returns the per-step response pairs.
    `algo_pool` pins the algorithm draw (r15 suite ids); default is
    the historical token/leaky mix (or token-only)."""
    rng = np.random.default_rng(seed)
    cfg = StoreConfig(rows=rows, slots=slots)
    on = TpuEngine(cfg, buckets=(64, 256),
                   sketch=SketchConfig(rows=4, width=1 << 12))
    off = TpuEngine(cfg, buckets=(64, 256))
    pool = _keys(keyspace)
    t = T0
    out = []
    for step in range(steps):
        n = int(rng.integers(1, 48))
        kh = pool[rng.integers(0, keyspace, n)]
        hits = rng.choice(hit_pool, n).astype(np.int64)
        limit = rng.choice(limit_pool, n).astype(np.int64)
        dur = rng.choice(dur_pool, n).astype(np.int64)
        if algo_pool is not None:
            algo = rng.choice(algo_pool, n).astype(np.int32)
        elif token_only:
            algo = np.zeros(n, np.int32)
        else:
            algo = rng.integers(0, 2, n).astype(np.int32)
        gnp = np.zeros(n, bool)
        t += int(rng.choice(dt_pool))
        a = on.decide_arrays(kh, hits, limit, dur, algo, gnp, t)
        b = off.decide_arrays(kh, hits, limit, dur, algo, gnp, t)
        out.append((step, a, b))
    return on, off, out


@pytest.mark.parametrize("seed", [2, 13])
def test_on_off_identity_no_pressure(seed):
    """With the exact tier under capacity (no dropped creates), sketch
    ON is byte-identical to OFF — responses AND store contents — for
    ALL FOUR algorithms (r21: sliding/GCRA are sketch-servable now, so
    the identity must keep holding with them in the stream)."""
    on, off, steps = _twin_arrays(
        seed, slots=1 << 10, rows=16, algo_pool=(0, 1, 2, 3)
    )
    for step, a, b in steps:
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y, err_msg=f"step {step}")
    assert on.stats.snapshot()["dropped"] == 0
    np.testing.assert_array_equal(
        np.asarray(on.store.data), np.asarray(off.store.data)
    )


def test_on_off_pressure_is_fail_closed():
    """Under tier pressure every divergent response row is
    at-least-as-restrictive with the tier on (status >=, remaining <=),
    and live-victim protection shows in the stats: the OFF engine
    churns resident windows (evictions), the ON engine serves those
    creates from the sketch instead (dropped == sketch-served) and
    evicts strictly less. One duration and sub-window clock advances
    keep every step inside one aligned window — across a window
    boundary the fixed-window tier legitimately forgives earlier than
    a creation-anchored window (the standard fixed-window artifact,
    bounded at 2x limit per duration and documented); hits <= limit
    keeps the oversized-hit creation corner (which reports
    remaining=limit by reference contract) to the no-pressure fuzz,
    and ONE limit keeps `remaining` comparable (a mixed-param stream
    answers from STORED params on the exact tier but request params on
    the sketch tier — both documented, not comparable row-wise). Unit
    hits make the row-wise claim airtight: with h in {0,1} the sketch
    estimate dominates the exact tier's current-window consumption for
    every key (both admit the same prefix until the sketch refuses
    first or the exact tier churns-and-forgets), so status can only
    tighten and remaining only shrink; variable hit sizes reorder
    refusals legitimately and belong to the admitted-count bound, not
    a row-wise one. Token-only for the same reason: an algorithm
    switch RECREATES a resident window (count reset), and residency
    differs between the engines under pressure, so mixed-algo streams
    reset counts at engine-dependent times (covered by the
    no-pressure and serving identity fuzzes instead)."""
    on, off, steps = _twin_arrays(
        7, slots=16, rows=1, steps=80, keyspace=64,
        hit_pool=(0, 1, 1, 1), limit_pool=(50,),
        dur_pool=(600_000,), dt_pool=(0, 1, 7, 150), token_only=True,
    )
    diverged = 0
    for step, a, b in steps:
        sa, la, ra, ta = a
        sb, lb, rb, tb = b
        differ = (sa != sb) | (ra != rb) | (ta != tb) | (la != lb)
        diverged += int(differ.sum())
        assert (sa >= sb).all(), f"fail-open status at step {step}"
        assert (ra <= rb).all(), f"fail-open remaining at step {step}"
    assert diverged > 0, "pressure fuzz never engaged the sketch"
    s_on, s_off = on.stats.snapshot(), off.stats.snapshot()
    assert s_on["dropped"] > 0
    # live-victim protection: resident windows survive the tail storm
    assert s_on["evictions"] < s_off["evictions"]


@pytest.mark.parametrize("algo", [2, 3], ids=["sliding", "gcra"])
def test_window_ring_pressure_is_fail_closed(algo):
    """r21 window-ring: sliding/GCRA creates dropped to way exhaustion
    are served from the ring (sliding blend / TAT-quantized GCRA) and
    every served row is AT-LEAST-AS-RESTRICTIVE than the r15 bypass
    behavior — the OFF engine serves each dropped create as a
    phantom-fresh window with the full budget, the most permissive
    answer possible, so ANY correct sketch serving must dominate it
    row-wise (status >=, remaining <=). All buckets are pinned with
    immortal filler found-writers so every measured create provably
    drops in BOTH engines (the OFF engine never persists a measured
    key — asserted), which keeps the comparison clean across rotation
    boundaries and clock jumps: the dt pool crosses single and
    multiple window advances. Unit hits and one limit keep `remaining`
    row-comparable (see test_on_off_pressure_is_fail_closed). Strict
    dominance vs the EXACT r15 oracle is deliberately not claimed —
    impossible once refusal histories diverge (module docstring); the
    bit-exact semantics are pinned against the host twins in
    test_window_ring_twin_oracle instead."""
    on = _pressure_engine()
    off = _pressure_engine(sketch=False)
    fillers = _pin_buckets(on)
    np.testing.assert_array_equal(fillers, _pin_buckets(off))
    nf = fillers.shape[0]
    rng = np.random.default_rng(11)
    keyspace = 40
    pool = _keys(keyspace, tag=3)
    DUR, LIM = 10_000, 6
    t = T0
    diverged = 0
    for step in range(60):
        n = int(rng.integers(1, 24))
        kh_m = pool[rng.integers(0, keyspace, n)]
        hits_m = rng.choice((0, 1, 1, 1), n).astype(np.int64)
        t += int(rng.choice((0, 1, 7, 500, 2500, 12_000, 21_000)))
        kh = np.concatenate([fillers, kh_m])
        hits = np.concatenate([np.zeros(nf, np.int64), hits_m])
        lim = np.full(nf + n, LIM, np.int64)
        lim[:nf] = 1000  # fillers keep their own params
        dur = np.full(nf + n, DUR, np.int64)
        dur[:nf] = 1_000_000_000
        al = np.full(nf + n, algo, np.int32)
        al[:nf] = 0
        gnp = np.zeros(nf + n, bool)
        a = on.decide_arrays(kh, hits, lim, dur, al, gnp, t)
        b = off.decide_arrays(kh, hits, lim, dur, al, gnp, t)
        sa, _, ra, _ = a
        sb, _, rb, _ = b
        differ = (sa[nf:] != sb[nf:]) | (ra[nf:] != rb[nf:])
        diverged += int(differ.sum())
        assert (sa[nf:] >= sb[nf:]).all(), f"fail-open status @{step}"
        assert (ra[nf:] <= rb[nf:]).all(), f"fail-open remaining @{step}"
    assert diverged > 0, "pressure fuzz never engaged the ring"
    assert on.stats.snapshot()["dropped"] > 0
    assert int(np.asarray(on.sketch.data).sum()) > 0, (
        "ring never charged: sliding/GCRA are sketch-servable in r21"
    )
    # the OFF engine (r15 bypass behavior) never persisted a measured
    # key — every step really was phantom-fresh over there
    assert not off.live_mask(pool, t).any()


@pytest.mark.parametrize(
    "skc",
    [
        SketchConfig(rows=4, width=1 << 12),
        SketchConfig(rows=2, width=1 << 12, counter_bytes=4),
    ],
    ids=["r13-int64", "v2-int32"],
)
@pytest.mark.parametrize("algo", [2, 3], ids=["sliding", "gcra"])
def test_window_ring_twin_oracle(algo, skc):
    """A sketch-served sliding/GCRA key is BIT-EXACT against its host
    twin (algorithms.sketch_sliding_budget / sketch_gcra_budget fed
    host-read ring estimates) on a pinned-bucket single-key drive
    whose clock crosses rotation boundaries, multi-window jumps and
    sub-window advances — and the ring never under-counts the true
    charge log (est_cur >= charges the engine admitted per window).
    Runs on both counter derivations: int64 (r13) and saturating
    int32 (v2)."""
    from gubernator_tpu.core.algorithms import (
        gcra_params,
        sketch_gcra_budget,
        sketch_sliding_budget,
    )

    I32_MAX = (1 << 31) - 1
    eng = TpuEngine(
        StoreConfig(rows=1, slots=16), buckets=(64, 256), sketch=skc
    )
    fillers = _pin_buckets(eng)
    nf = fillers.shape[0]
    key = _keys(1, tag=11)[:1]
    DUR, LIM = 10_000, 4
    epoch = T0 - 1  # EpochClock pins one ms before first contact
    true_charges: dict = {}

    def ring_est(wid):
        data = np.asarray(eng.sketch.data)
        idx = sketch_indices_np(
            key, np.array([wid], np.int64), skc
        )
        return int(
            min(data[r, idx[r][0]] for r in range(skc.rows))
        )

    t = T0
    for dt in (0, 1, 1, 1, 1, 1, 3000, 1, 1, 6000, 1, 1, 15_000,
               1, 1, 1, 1, 25_001, 1, 2, 3, 9_999, 1):
        t += dt
        e_now = t - epoch
        wid = e_now // DUR
        est_cur = ring_est(wid)
        est_prev = ring_est(wid - 1)
        if algo == 2:
            budget, wend = sketch_sliding_budget(
                est_cur, est_prev, e_now, LIM, DUR
            )
            exp_reset = epoch + wend
        else:
            budget, tatq = sketch_gcra_budget(
                est_cur, est_prev, e_now, LIM, DUR
            )
            T_, tau = gcra_params(LIM, DUR)
            tatq_c = min(tatq, I32_MAX)
            if budget >= 1:  # this row charges
                exp_reset = epoch + min(tatq_c + T_, I32_MAX)
            else:
                exp_reset = epoch + min(tatq_c + T_ - tau, I32_MAX)
        charged = budget >= 1
        exp_status = Status.UNDER_LIMIT if charged else Status.OVER_LIMIT
        exp_rem = budget - 1 if charged else 0
        kh = np.concatenate([fillers, key])
        hits = np.concatenate([np.zeros(nf, np.int64), [1]])
        lim = np.full(nf + 1, LIM, np.int64)
        lim[:nf] = 1000
        dur = np.full(nf + 1, DUR, np.int64)
        dur[:nf] = 1_000_000_000
        al = np.full(nf + 1, algo, np.int32)
        al[:nf] = 0
        s, l, r, ts = eng.decide_arrays(
            kh, hits, lim, dur, al, np.zeros(nf + 1, bool), t
        )
        assert s[-1] == int(exp_status), f"status @t={t}"
        assert r[-1] == exp_rem, f"remaining @t={t}"
        assert ts[-1] == exp_reset, f"reset @t={t}"
        assert l[-1] == LIM
        if charged:
            true_charges[wid] = true_charges.get(wid, 0) + 1
            # zero under-count: the ring re-read AFTER the charge
            # covers everything admitted this window
            assert ring_est(wid) >= true_charges[wid]
    assert len(true_charges) >= 3, "drive never crossed rotations"
    assert sum(true_charges.values()) > 0


def test_on_off_identity_serving_device(monkeypatch):
    """The serve-level mirror of the identity fuzz: GUBER_SKETCH on vs
    off through the REAL pipeline (instance -> batcher -> arrival prep
    -> merged submit -> kernel, tpu-on-cpu) under the r10 fake clock,
    with an under-capacity store — byte-identical responses, clock
    advances crossing reset boundaries mid-fuzz."""
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    def be(sk: bool):
        return TpuBackend(
            StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64),
            sketch=SketchConfig(rows=4, width=1 << 12) if sk else None,
        )

    async def mk(sk: bool):
        conf = ServerConfig(
            grpc_address=ADDR, advertise_address=ADDR, sketch=sk,
            # a huge tick so no promoter flush fires mid-fuzz; the
            # promoter is inert anyway with zero drops
            sketch_sync_wait=600.0,
        )
        inst = Instance(conf, be(sk))
        inst.start()
        await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
        return inst

    async def run():
        on = await mk(True)
        off = await mk(False)
        assert on.promoter is not None and off.promoter is None
        if on.shed is not None:
            on.shed.now_fn = clock
        if off.shed is not None:
            off.shed.now_fn = clock
        try:
            rng = np.random.default_rng(9)
            keys = [f"s{i}" for i in range(14)]
            for step in range(140):
                clock.t += int(rng.choice([0, 1, 7, 150, 2500]))
                n = int(rng.integers(1, 7))
                batch = [
                    RateLimitReq(
                        name="skfuzz",
                        unique_key=keys[int(rng.integers(len(keys)))],
                        hits=int(rng.choice([0, 1, 1, 2, 9])),
                        limit=int(rng.choice([1, 2, 3, 50])),
                        duration=int(rng.choice([400, 2000, 60_000])),
                        algorithm=Algorithm(int(rng.integers(2))),
                    )
                    for _ in range(n)
                ]
                a = await on.get_rate_limits(batch)
                b = await off.get_rate_limits(batch)
                for x, y, r in zip(a, b, batch):
                    assert (
                        x.status, x.limit, x.remaining, x.reset_time,
                        x.error,
                    ) == (
                        y.status, y.limit, y.remaining, y.reset_time,
                        y.error,
                    ), (step, r, x, y)
            assert on.backend.stats()["dropped"] == 0
        finally:
            await on.stop()
            await off.stop()

    asyncio.run(run())


# -- error bound property ---------------------------------------------------


def test_tail_error_bound_and_no_undercount():
    """The committed acceptance property on a pinned zipf stream
    (cli/bench_serving.measure_tail_error, the same code path the
    BENCH_SKETCH_r21.json artifact runs): zero under-counts and max
    overestimate within the documented e*N/width bound — on the v2
    default AND under the r21 window-ring arms (sliding/GCRA charge the
    same per-window cells, so the one-sided bound carries over)."""
    from gubernator_tpu.cli.bench_serving import measure_tail_error

    err = measure_tail_error(batches=16)
    assert err["derivation"] == "v2" and err["counter_bytes"] == 4
    assert err["under_counts"] == 0, err
    assert err["within_bound"], err
    assert err["charged_hits"] > 0 and err["distinct_keys"] > 100
    for arm in ("sliding", "gcra"):
        e = measure_tail_error(batches=8, algorithm=arm)
        assert e["under_counts"] == 0, (arm, e)
        assert e["within_bound"], (arm, e)
        assert e["charged_hits"] > 0


def test_tail_error_derivation_ab_is_strictly_tighter():
    """The r21 derivation A/B (measure_tail_error_ab): at the SAME byte
    budget v2's bound is 4x tighter than r13's (2 rows of int32 -> 4x
    width) and its measured max overestimate sits strictly below r13's
    THEORETICAL bound — the per-byte win the tentpole commits — with
    zero under-counts on both geometries."""
    from gubernator_tpu.cli.bench_serving import measure_tail_error_ab

    ab = measure_tail_error_ab(batches=16)
    assert ab["zero_under_counts"], ab
    assert ab["v2_max_below_r13_bound"], ab
    # 4x width = 4x tighter bound (ratio reported off the rounded
    # bounds, so pin the exact geometry instead of the float)
    assert abs(ab["v2_bound_over_r13_bound"] - 0.25) < 0.01
    assert ab["v2"]["sketch_width"] == 4 * ab["r13"]["sketch_width"]
    assert ab["v2"]["within_bound"] and ab["r13"]["within_bound"]


# -- eviction -> sketch migration (r14) -------------------------------------


def _same_bucket_keys(slots: int, n: int, start: int = 1):
    """n distinct-fingerprint uint64 hashes all mapping to bucket 0."""
    from gubernator_tpu.core import hashing
    from gubernator_tpu.core.store import _BUCKET_SALT

    out = []
    v = start
    while len(out) < n:
        kh = np.uint64(v << 32) | np.uint64(5)
        b = int(
            hashing.mix64(np.asarray([kh], np.uint64) ^ _BUCKET_SALT)[0]
            & np.uint64(slots - 1)
        )
        if b == 0:
            out.append(kh)
        v += 1
    return np.asarray(out, np.uint64)


def test_evicted_dead_entry_folds_into_sketch(monkeypatch):
    """A create recycling a DEAD victim's way folds the victim's
    consumed count into the victim key's current fixed window: the
    evicted-then-recreated key decides at-least-as-restrictively as
    the unevicted oracle, and the sketch estimate actually carries
    the folded count (without the fold it would be 0). Exactly
    window-aligned dead entries (no overlap with the current window)
    fold nothing."""

    def mk():
        eng = TpuEngine(
            StoreConfig(rows=1, slots=16), buckets=(64,),
            sketch=SketchConfig(rows=4, width=1 << 12),
        )
        # pin the epoch at T0 (engine-ms 0)
        z = np.zeros(1, np.int64)
        eng.decide_arrays(
            _keys(1, tag=250), z, z + 1, z + 1000,
            np.zeros(1, np.int32), np.zeros(1, bool), T0,
        )
        return eng

    D, LIM = 1000, 10
    K, L = _same_bucket_keys(16, 2)
    kk = np.asarray([K], np.uint64)
    ll = np.asarray([L], np.uint64)

    def drive(eng, kh, hits, t):
        one = np.ones(1, np.int64)
        return eng.decide_arrays(
            kh, np.asarray([hits], np.int64), one * LIM, one * D,
            np.zeros(1, np.int32), np.zeros(1, bool), T0 + t,
        )

    evicted = mk()
    oracle = mk()
    for eng in (evicted, oracle):
        # K created mid-window-1 (engine 1500): window [1500, 2500)
        # consumes 6 of 10 — its tail crosses into fixed window 2
        drive(eng, kk, 6, 1500)
    # at engine 2600 K is dead (2500 < 2600); L's create recycles K's
    # way on `evicted` only — the fold moment
    drive(evicted, ll, 1, 2600)
    assert evicted.stats.snapshot()["evictions"] == 1
    est = evicted.sketch_estimates(kk, np.asarray([D], np.int64), T0 + 2600)
    assert est[0] >= 6, f"fold did not land: estimate {est[0]}"

    # K returns at 2700: bucket full with LIVE L -> sketch-served from
    # the folded estimate; the unevicted oracle recreates exactly
    s_e, _, r_e, t_e = drive(evicted, kk, 1, 2700)
    s_o, _, r_o, t_o = drive(oracle, kk, 1, 2700)
    assert s_o[0] == int(Status.UNDER_LIMIT) and r_o[0] == LIM - 1
    assert s_e[0] >= s_o[0] and r_e[0] <= r_o[0], (
        "evicted-then-recreated key went fail-open vs the unevicted "
        f"oracle: {(s_e[0], r_e[0])} vs {(s_o[0], r_o[0])}"
    )
    # the folded 6 plus this charge: remaining = (10 - 6) - 1
    assert r_e[0] == LIM - 6 - 1
    # sketch window reset = window 2's end (engine 3000; the epoch
    # pins 1ms before first contact since r15, so unix = T0-1+3000)
    assert t_e[0] == (T0 - 1) + 3000

    # exact alignment: an entry whose expiry == the window boundary
    # has NO overlap with the current window -> nothing folds
    aligned = mk()
    K2, L2 = _same_bucket_keys(16, 2, start=500)
    # unix T0+999 = ENGINE 1000 (epoch at T0-1): window [1000, 2000)
    # ends exactly on the fixed-window boundary
    drive(aligned, np.asarray([K2], np.uint64), 6, 999)
    drive(aligned, np.asarray([L2], np.uint64), 1, 2100)  # recycles
    est2 = aligned.sketch_estimates(
        np.asarray([K2], np.uint64), np.asarray([D], np.int64), T0 + 2100
    )
    assert est2[0] == 0, est2


def test_sticky_over_victim_folds_whole_limit(monkeypatch):
    """A recycled sticky-over victim folds its LIMIT: the key stays
    refused for the remainder of its current fixed window when it
    returns sketch-served."""
    eng = TpuEngine(
        StoreConfig(rows=1, slots=16), buckets=(64,),
        sketch=SketchConfig(rows=4, width=1 << 12),
    )
    z = np.zeros(1, np.int64)
    eng.decide_arrays(
        _keys(1, tag=251), z, z + 1, z + 1000,
        np.zeros(1, np.int32), np.zeros(1, bool), T0,
    )
    D, LIM = 1000, 4
    K, L = _same_bucket_keys(16, 2, start=900)

    def drive(kh, hits, t):
        one = np.ones(1, np.int64)
        return eng.decide_arrays(
            np.asarray([kh], np.uint64), np.asarray([hits], np.int64),
            one * LIM, one * D, np.zeros(1, np.int32),
            np.zeros(1, bool), T0 + t,
        )

    # drain K to 0 then over: sticky flag set, remaining 0
    drive(K, 4, 1500)
    s, _, r, _ = drive(K, 1, 1600)
    assert s[0] == int(Status.OVER_LIMIT)
    # dead at 2600; L recycles the way; K returns sketch-served
    drive(L, 1, 2600)
    s2, _, r2, _ = drive(K, 1, 2700)
    assert s2[0] == int(Status.OVER_LIMIT) and r2[0] == 0, (s2, r2)


# -- promotion / demotion ---------------------------------------------------


def test_promote_migrates_estimate_and_skips_live():
    """Promotion installs remaining = limit - estimate with reset = the
    window end; the key then decides EXACTLY (store-resident) and a
    second promote skips it (live)."""
    eng = _pressure_engine()
    fillers = _pin_buckets(eng)
    nf = fillers.shape[0]
    key = _keys(1, tag=9)[:1]
    DUR, LIM = 600_000, 10
    for i in range(3):  # est -> 3
        kh = np.concatenate([fillers, key])
        hits = np.concatenate([np.zeros(nf, np.int64), [1]])
        eng.decide_arrays(
            kh, hits, np.full(nf + 1, LIM, np.int64),
            np.full(nf + 1, DUR, np.int64),
            np.zeros(nf + 1, np.int32), np.zeros(nf + 1, bool), T0 + i,
        )
    assert not eng.live_mask(key, T0 + 5)[0]
    inst, est, reset, over = eng.promote_from_sketch(
        key, np.array([LIM]), np.array([DUR]), T0 + 5
    )
    assert inst[0] and est[0] == 3 and not over[0]
    # window end; the epoch pins 1ms before first contact (r15)
    assert reset[0] == (T0 - 1) + DUR
    assert eng.live_mask(key, T0 + 6)[0]
    # the window CONTINUES: next hit decides exactly at remaining 6
    kh = np.concatenate([fillers, key])
    hits = np.concatenate([np.zeros(nf, np.int64), [1]])
    s, l, r, t = eng.decide_arrays(
        kh, hits, np.full(nf + 1, LIM, np.int64),
        np.full(nf + 1, DUR, np.int64), np.zeros(nf + 1, np.int32),
        np.zeros(nf + 1, bool), T0 + 6,
    )
    assert s[-1] == int(Status.UNDER_LIMIT) and r[-1] == LIM - 3 - 1
    # re-promoting skips the live key and must not clobber its state
    inst2, _, _, _ = eng.promote_from_sketch(
        key, np.array([LIM]), np.array([DUR]), T0 + 7
    )
    assert not inst2[0]
    s, l, r, t = eng.decide_arrays(
        kh, hits, np.full(nf + 1, LIM, np.int64),
        np.full(nf + 1, DUR, np.int64), np.zeros(nf + 1, np.int32),
        np.zeros(nf + 1, bool), T0 + 8,
    )
    assert r[-1] == LIM - 3 - 2


def test_promoter_flow_and_shed_feed():
    """Instance-level promoter loop: hot sketch-tier keys promote on a
    flush tick, over-limit candidates seed the shed cache, and expired
    promotions demote."""
    conf = ServerConfig(
        grpc_address=ADDR, advertise_address=ADDR,
        sketch_sync_wait=600.0,  # manual ticks only
        sketch_topk=64,
    )
    backend = TpuBackend(
        StoreConfig(rows=1, slots=16), buckets=(64, 256),
        sketch=SketchConfig(rows=4, width=1 << 12),
    )

    async def run():
        inst = Instance(conf, backend)
        inst.start()
        await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
        try:
            assert inst.promoter is not None
            # force the observer to sample every dispatch
            inst.promoter.tracker._next = 0.0
            import gubernator_tpu.serve.promoter as prom_mod

            orig = prom_mod.OBSERVE_MIN_INTERVAL_S
            prom_mod.OBSERVE_MIN_INTERVAL_S = 0.0
            try:
                reqs = [
                    RateLimitReq(
                        name="p", unique_key=f"pk{j}", hits=1,
                        limit=2, duration=600_000,
                    )
                    for j in range(64)
                ]
                for _ in range(4):  # drive the tail over limit
                    await inst.get_rate_limits(reqs)
            finally:
                prom_mod.OBSERVE_MIN_INTERVAL_S = orig
            assert backend.stats()["dropped"] > 0
            shed_before = len(inst.shed)
            await inst.promoter.flush_once()
            st = inst.promoter.stats()
            assert st["promotions"] > 0
            assert st["shed_seeds"] > 0
            assert len(inst.shed) >= shed_before
            # promoted keys are now exact-resident
            from gubernator_tpu.core.hashing import slot_hash_batch

            promoted = np.array(
                sorted(inst.promoter._promoted), np.uint64
            )
            live = backend.engine.live_mask(promoted)
            assert live.any()
            # demotion: expire every promotion and tick again
            inst.promoter._promoted = {
                h: 0 for h in inst.promoter._promoted
            }
            await inst.promoter.flush_once()
            assert inst.promoter.stats()["demotions"] > 0
        finally:
            await inst.stop()

    asyncio.run(run())


def test_shed_seed_gates():
    clock = FakeClock()
    c = ShedCache(2, now_fn=clock)
    c.seed(1, 5, 1000, clock.t + 500)
    r = RateLimitReq(name="n", unique_key="k", hits=1, limit=5,
                     duration=1000)
    assert c.lookup_resp(1, r).reset_time == clock.t + 500
    c.seed(2, 5, 1000, clock.t - 1)  # expired: ignored
    assert 2 not in c._entries
    c.seed(3, 5, 1000, clock.t + 500)
    c.seed(4, 5, 1000, clock.t + 500)  # capacity 2: LRU evicts
    assert len(c) == 2 and 1 not in c._entries


def test_committed_artifact_headline():
    """BENCH_SKETCH_r13.json: the committed acceptance — the tier
    actually engaged (drops served), zero under-counts, error within
    bound; a missed throughput target must carry the scoping note."""
    import json
    import pathlib

    doc = json.loads(
        (
            pathlib.Path(__file__).resolve().parent.parent
            / "BENCH_SKETCH_r13.json"
        ).read_text()
    )
    assert doc["acceptance"]["error_met"] is True
    assert doc["tail_error"]["under_counts"] == 0
    assert doc["tail_error"]["within_bound"] is True
    sk = next(
        r for r in doc["rows"] if r["metric"] == "zipf100m_sketch_tier"
    )
    assert sk["dropped_creates"] > 0, "the sketch tier never engaged"
    assert doc["key_space"] >= 100_000_000
    assert doc["acceptance"]["throughput_met"] or doc["acceptance_note"]


def test_committed_artifact_headline_r21():
    """BENCH_SKETCH_r21.json: the r21 acceptance — v2's measured max
    overestimate strictly below the r13 bound at the same budget with
    zero under-counts anywhere, and the sliding/GCRA arms actually
    served from the window-ring at 100M-key cardinality."""
    import json
    import pathlib

    doc = json.loads(
        (
            pathlib.Path(__file__).resolve().parent.parent
            / "BENCH_SKETCH_r21.json"
        ).read_text()
    )
    acc = doc["acceptance"]
    assert acc["error_met"] is True
    assert acc["derivation_met"] is True
    assert acc["arms_met"] is True
    ab = doc["tail_error_derivation_ab"]
    assert ab["v2_max_below_r13_bound"] is True
    assert ab["zero_under_counts"] is True
    assert ab["v2"]["documented_bound"] < ab["r13"]["documented_bound"]
    for arm in ("sliding", "gcra"):
        e = doc["tail_error_arms"][arm]
        assert e["under_counts"] == 0 and e["within_bound"] is True
        row = next(
            r
            for r in doc["rows"]
            if r["metric"] == f"zipf100m_sketch_{arm}"
        )
        assert row["dropped_creates"] > 0, f"{arm} arm never engaged"
    assert doc["key_space"] >= 100_000_000
    assert acc["throughput_met"] or doc["acceptance_note"]


# -- shared key streams -----------------------------------------------------


def test_keystreams_bit_identical_and_churn_disjoint():
    """The factored zipf recipe reproduces the historical inline recipe
    bit for bit, and churn phases present disjoint key sets."""
    from gubernator_tpu.cli import keystreams

    rng = np.random.default_rng(42)
    zipf = rng.zipf(1.2, size=4096) % 10_000_000
    legacy = (
        zipf.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    ) ^ np.uint64(0xDEADBEEFCAFEF00D)
    np.testing.assert_array_equal(
        keystreams.zipf_pool(10_000_000, 4096), legacy
    )
    a = keystreams.churn_pool(1 << 30, 4096, phase=0)
    b = keystreams.churn_pool(1 << 30, 4096, phase=1)
    assert np.intersect1d(a, b).size == 0
    assert keystreams.stream_pool("zipf", 1000, 64).shape == (64,)
    with pytest.raises(ValueError):
        keystreams.stream_pool("nope", 1000, 64)


def test_spacesaving_weighted_payload_decay():
    from gubernator_tpu.core.sketches import SpaceSaving

    ss = SpaceSaving(capacity=3)
    ss.observe_weighted({1: 10, 2: 5}, payloads={1: ("a", 1)})
    ss.observe_weighted({3: 2, 4: 8})  # 4 evicts 3 (min) at capacity
    top = ss.top_with_payload(3)
    assert top[0][0] == 1 and top[0][3] == ("a", 1)
    assert ss.payload(2) is None
    ss.decay(shift=3)  # 10>>3=1, 5>>3=0 (dropped), 8+2>>3...
    assert 1 in ss._counts and 2 not in ss._counts
    assert ss.payload(1) == ("a", 1)
