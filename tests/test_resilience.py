"""Peer resilience layer (r8): circuit breaker transitions, retry
policy + budget exhaustion, degraded mode, breaker-aware health, the
GlobalManager's supervised restarts, and graceful drain — the fast
in-process matrix behind the chaos soak (test_chaos_soak.py runs the
kill-a-real-node version, marked slow).
"""

import asyncio
import struct

import grpc
import pytest

from gubernator_tpu.api import convert
from gubernator_tpu.api.proto.gen import peers_pb2
from gubernator_tpu.api.types import (
    Behavior,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
)
from gubernator_tpu.serve.backends import ExactBackend
from gubernator_tpu.serve.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerOpenError,
    CircuitBreaker,
)
from gubernator_tpu.serve.config import BehaviorConfig, ServerConfig
from gubernator_tpu.serve.instance import Instance
from gubernator_tpu.serve.peers import PeerClient, is_retryable


def _req(key="k", hits=1, behavior=Behavior.BATCHING) -> RateLimitReq:
    return RateLimitReq(
        name="res", unique_key=key, hits=hits, limit=10, duration=60000,
        behavior=behavior,
    )


# -- circuit breaker state machine ----------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _tripped(b):
    for _ in range(b.failures):
        assert b.acquire()
        b.record_failure()
    return b


def test_breaker_trips_on_consecutive_failures_and_fails_fast():
    clk = _Clock()
    b = _tripped(CircuitBreaker(failures=3, cooldown=1.0, clock=clk))
    assert b.state == OPEN
    assert not b.acquire()  # fail fast, no probe before cooldown


def test_breaker_half_open_probe_closes_on_success():
    clk = _Clock()
    b = _tripped(CircuitBreaker(failures=3, cooldown=1.0, clock=clk))
    clk.t = 1.5  # past cooldown
    assert b.acquire()  # the half-open probe
    assert b.state == HALF_OPEN
    assert not b.acquire()  # probes bounded (probes=1)
    b.record_success()
    assert b.state == CLOSED
    assert b.acquire()


def test_breaker_probe_failure_reopens_and_restarts_cooldown():
    clk = _Clock()
    b = _tripped(CircuitBreaker(failures=3, cooldown=1.0, clock=clk))
    clk.t = 1.5
    assert b.acquire()
    b.record_failure()  # probe failed
    assert b.state == OPEN
    clk.t = 2.0  # cooldown restarted at 1.5 — still open
    assert not b.acquire()
    clk.t = 2.6
    assert b.acquire()
    b.record_success()
    assert b.state == CLOSED


def test_breaker_ratio_trip_without_consecutive_failures():
    # alternate ok/fail: never 3 consecutive, but 50% failures over a
    # full window must trip
    b = CircuitBreaker(failures=3, ratio=0.5, window=8, cooldown=1.0,
                       clock=_Clock())
    for i in range(8):
        assert b.acquire()
        (b.record_failure if i % 2 else b.record_success)()
    assert b.state == OPEN


def test_breaker_transition_callback():
    seen = []
    clk = _Clock()
    b = CircuitBreaker(failures=2, cooldown=1.0, clock=clk,
                       on_transition=lambda f, t: seen.append((f, t)))
    _tripped(b)
    clk.t = 2.0
    b.acquire()
    b.record_success()
    assert seen == [
        (CLOSED, OPEN), (OPEN, HALF_OPEN), (HALF_OPEN, CLOSED)
    ]


def test_breaker_effective_state_half_open_without_traffic():
    """An idle breaker past its cooldown must READ as half-open (code
    1), or health/metrics would report a recovered peer as down
    forever once traffic was routed away (no acquire -> the lazy
    OPEN->HALF_OPEN transition never runs)."""
    clk = _Clock()
    b = _tripped(CircuitBreaker(failures=3, cooldown=1.0, clock=clk))
    assert b.effective_state() == OPEN and b.state_code == 2
    clk.t = 1.5  # cooldown elapsed, NO acquire happened
    assert b.state == OPEN  # stored state unchanged (lazy)
    assert b.effective_state() == HALF_OPEN and b.state_code == 1


def test_breaker_stale_outcome_cannot_close_or_reopen():
    """A slow call admitted while CLOSED that resolves during a later
    half-open must not masquerade as a probe: its success must not
    close the breaker, its failure must not restart the cooldown
    (acquire/record straddle the RPC await, so this interleaving is
    real — a hung call outliving the trip)."""
    clk = _Clock()
    b = CircuitBreaker(failures=3, cooldown=1.0, probes=1, clock=clk)
    straggler = b.acquire()  # admitted while CLOSED, then hangs
    _tripped(b)  # meanwhile fast calls trip the breaker
    clk.t = 1.5
    probe = b.acquire()  # the real half-open probe, in flight
    assert b.state == HALF_OPEN
    b.record_success(straggler)  # straggler resolves late
    assert b.state == HALF_OPEN  # NOT closed by the stale success
    b.record_failure(straggler)
    assert b.state == HALF_OPEN  # NOT re-opened by the stale failure
    b.record_success(probe)  # only the true probe decides
    assert b.state == CLOSED


# -- retry policy ----------------------------------------------------------


class _UnavailableError(grpc.RpcError):
    def code(self):
        return grpc.StatusCode.UNAVAILABLE


def test_is_retryable_classification():
    assert is_retryable(_UnavailableError())
    assert is_retryable(ConnectionRefusedError())
    assert not is_retryable(asyncio.TimeoutError())  # may have applied
    assert not is_retryable(RuntimeError("boom"))
    # a pure-peek batch is idempotent: anything retries
    assert is_retryable(asyncio.TimeoutError(), all_peek=True)
    assert is_retryable(RuntimeError("boom"), all_peek=True)


class _FlakyStub:
    """Fails the first `fail_n` calls with UNAVAILABLE, then succeeds."""

    def __init__(self, fail_n):
        self.fail_n = fail_n
        self.calls = 0

    async def GetPeerRateLimits(self, pb_req, timeout=None):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise _UnavailableError()
        return peers_pb2.GetPeerRateLimitsResp(
            rate_limits=[
                convert.resp_to_pb(RateLimitResp(limit=10, remaining=9))
                for _ in pb_req.requests
            ]
        )


def _client(stub, **kw) -> PeerClient:
    defaults = dict(peer_retries=2, peer_backoff=0.001,
                    peer_backoff_max=0.002)
    defaults.update(kw)
    c = PeerClient(BehaviorConfig(**defaults), "127.0.0.1:1")
    c.stub = stub
    return c


def test_retry_masks_transient_unavailable():
    async def run():
        stub = _FlakyStub(fail_n=2)
        c = _client(stub)
        resps = await c.get_peer_rate_limits([_req()])
        assert resps[0].remaining == 9
        assert stub.calls == 3  # 2 failures + 1 success

    asyncio.run(run())


def test_retry_budget_exhaustion_raises():
    async def run():
        stub = _FlakyStub(fail_n=100)
        c = _client(stub, peer_retries=2)
        with pytest.raises(grpc.RpcError):
            await c.get_peer_rate_limits([_req()])
        assert stub.calls == 3  # initial + 2 retries, then give up

    asyncio.run(run())


def test_no_retry_for_nonretryable_on_hit_batch():
    class _DeadlineStub:
        calls = 0

        async def GetPeerRateLimits(self, pb_req, timeout=None):
            self.calls += 1
            raise RuntimeError("application error")

    async def run():
        stub = _DeadlineStub()
        c = _client(stub)
        with pytest.raises(RuntimeError):
            await c.get_peer_rate_limits([_req(hits=1)])
        assert stub.calls == 1  # hits may have applied: never re-sent

    asyncio.run(run())


def test_peek_batch_retries_any_failure():
    class _FlakyAppStub:
        calls = 0

        async def GetPeerRateLimits(self, pb_req, timeout=None):
            self.calls += 1
            if self.calls == 1:
                raise RuntimeError("transient application error")
            return peers_pb2.GetPeerRateLimitsResp(
                rate_limits=[
                    convert.resp_to_pb(RateLimitResp(limit=10, remaining=10))
                    for _ in pb_req.requests
                ]
            )

    async def run():
        stub = _FlakyAppStub()
        c = _client(stub)
        resps = await c.get_peer_rate_limits([_req(hits=0)])
        assert resps[0].remaining == 10
        assert stub.calls == 2

    asyncio.run(run())


def test_deadline_bounds_hung_stub():
    class _HungStub:
        async def GetPeerRateLimits(self, pb_req, timeout=None):
            await asyncio.Event().wait()

    async def run():
        c = _client(_HungStub(), peer_timeout=0.05, peer_retries=0)
        t0 = asyncio.get_running_loop().time()
        with pytest.raises(asyncio.TimeoutError):
            await c.get_peer_rate_limits([_req()])
        assert asyncio.get_running_loop().time() - t0 < 1.0

    asyncio.run(run())


def test_breaker_fails_fast_after_trip():
    async def run():
        stub = _FlakyStub(fail_n=10**9)
        c = _client(stub, peer_retries=0, breaker_failures=3,
                    breaker_cooldown=60.0)
        for _ in range(3):
            with pytest.raises(grpc.RpcError):
                await c.get_peer_rate_limits([_req()])
        calls = stub.calls
        with pytest.raises(BreakerOpenError):
            await c.get_peer_rate_limits([_req()])
        assert stub.calls == calls  # no RPC attempted while open

    asyncio.run(run())


def test_trip_failure_raises_root_cause_not_breaker_error():
    """When the failure that trips the breaker is itself retryable,
    the caller must get THAT error immediately — not a backoff sleep
    followed by BreakerOpenError masking the root cause."""

    async def run():
        stub = _FlakyStub(fail_n=10**9)
        # breaker_failures=2, retries allowed: the 2nd attempt's
        # UNAVAILABLE trips the breaker mid-retry-loop
        c = _client(stub, peer_retries=5, breaker_failures=2,
                    breaker_cooldown=60.0)
        with pytest.raises(_UnavailableError):
            await c.get_peer_rate_limits([_req()])
        assert stub.calls == 2  # stopped at the trip, no wasted retries

    asyncio.run(run())


# -- instance-level: per-item errors, degraded mode, health ---------------


def _conf(**kw) -> ServerConfig:
    conf = ServerConfig(
        grpc_address="127.0.0.1:1",
        advertise_address="127.0.0.1:1",
        backend="exact",
        behaviors=BehaviorConfig(
            peer_timeout=0.2, peer_retries=1, peer_backoff=0.001,
            peer_backoff_max=0.002, breaker_failures=3,
            breaker_cooldown=60.0,
        ),
    )
    for k, v in kw.items():
        setattr(conf, k, v)
    return conf


async def _instance_with_dead_peer(conf):
    """Instance owning nothing: all keys route to a peer address
    nothing listens on (connect-refused surfaces at RPC time, like the
    reference)."""
    from tests._util import free_ports

    dead = f"127.0.0.1:{free_ports(1)[0]}"
    inst = Instance(conf, ExactBackend(1000))
    inst.start()
    await inst.set_peers([
        PeerInfo(address=conf.advertise_address, is_owner=True),
        PeerInfo(address=dead, is_owner=False),
    ])
    # find keys the DEAD peer owns
    keys = []
    for i in range(256):
        r = _req(key=f"k{i}")
        if inst.get_peer(r.hash_key()).host == dead:
            keys.append(r)
        if len(keys) >= 4:
            break
    assert keys, "no key landed on the dead peer in 256 tries"
    return inst, dead, keys


def test_retry_exhaustion_surfaces_per_item_errors_not_exceptions():
    async def run():
        inst, dead, keys = await _instance_with_dead_peer(_conf())
        try:
            resps = await inst.get_rate_limits(keys)
            for r in resps:
                assert "from peer" in r.error  # per-item, not a 503
        finally:
            await inst.stop()

    asyncio.run(run())


def test_degraded_mode_answers_locally_with_metadata():
    async def run():
        inst, dead, keys = await _instance_with_dead_peer(
            _conf(degraded_local=True)
        )
        try:
            resps = await inst.get_rate_limits(keys)
            for r in resps:
                assert r.error == ""
                assert r.metadata["degraded"] == "true"
                assert r.metadata["owner"] == dead
                assert r.remaining == 9  # decided by the LOCAL store
            # hits actually landed locally: a second round decrements
            resps = await inst.get_rate_limits(keys)
            for r in resps:
                assert r.remaining == 8
        finally:
            await inst.stop()

    asyncio.run(run())


def test_health_reports_open_breaker():
    async def run():
        inst, dead, keys = await _instance_with_dead_peer(_conf())
        try:
            assert inst.health_check().status == "healthy"
            # trip the dead peer's breaker (breaker_failures=3, retries
            # count too: 2 attempts/request)
            for _ in range(3):
                await inst.get_rate_limits(keys[:1])
            h = inst.health_check()
            assert h.status == "unhealthy"
            assert "circuit open" in h.message and dead in h.message
        finally:
            await inst.stop()

    asyncio.run(run())


# -- GlobalManager supervision --------------------------------------------


def test_global_loops_restart_with_metric():
    from gubernator_tpu.serve import metrics
    from gubernator_tpu.serve.global_mgr import GlobalManager

    async def run():
        class _Inst:
            def get_peer(self, key):
                raise RuntimeError("unused")

            def peer_list(self):
                return []

        mgr = GlobalManager(
            BehaviorConfig(global_sync_wait=0.001), _Inst()
        )
        sent = []
        killed = asyncio.Event()

        async def dying_send(hits):
            killed.set()
            raise RuntimeError("injected loop death")

        async def recording_send(hits):
            sent.append(hits)

        mgr._send_hits = dying_send
        before = metrics.GLOBAL_TASK_RESTARTS.labels(
            task="async_hits"
        )._value.get()
        mgr.start()
        try:
            mgr.queue_hit(_req(key="g1", behavior=Behavior.GLOBAL))
            await asyncio.wait_for(killed.wait(), 5)
            # loop died; the supervisor must restart it and the next
            # queued hit must flow
            mgr._send_hits = recording_send
            for _ in range(200):
                mgr.queue_hit(_req(key="g2", behavior=Behavior.GLOBAL))
                if sent:
                    break
                await asyncio.sleep(0.02)
            assert sent, "async-hits loop never came back"
            assert metrics.GLOBAL_TASK_RESTARTS.labels(
                task="async_hits"
            )._value.get() > before
        finally:
            await mgr.stop()

    asyncio.run(run())


# -- graceful drain --------------------------------------------------------


def test_batcher_drain_waits_for_inflight_work():
    from gubernator_tpu.serve.batcher import DeviceBatcher

    class _SlowBackend:
        def decide(self, reqs, gnp):
            import time

            time.sleep(0.05)
            return [RateLimitResp(limit=r.limit, remaining=1)
                    for r in reqs]

        def update_globals(self, updates):
            pass

    async def run():
        b = DeviceBatcher(_SlowBackend(), batch_wait=0.0)
        b.start()
        futs = [asyncio.ensure_future(b.decide([_req(key=f"d{i}")],
                                               [False]))
                for i in range(4)]
        await asyncio.sleep(0)  # let them enqueue
        await asyncio.wait_for(b.drain(), 10)
        assert all(f.done() for f in futs)
        for f in futs:
            assert (await f)[0].remaining == 1
        await b.stop()

    asyncio.run(run())


def test_global_mgr_drain_flushes_pending():
    from gubernator_tpu.serve.global_mgr import GlobalManager

    async def run():
        class _Inst:
            def get_peer(self, key):
                raise RuntimeError("unused")

            def peer_list(self):
                return []

        # LONG sync window: without drain() these hits would sit for 60s
        mgr = GlobalManager(BehaviorConfig(global_sync_wait=60.0), _Inst())
        sent = []

        async def recording_send(hits):
            sent.append(hits)

        mgr._send_hits = recording_send
        mgr.queue_hit(_req(key="g1", behavior=Behavior.GLOBAL))
        await mgr.drain()
        assert len(sent) == 1 and "res_g1" in sent[0]

    asyncio.run(run())


def test_edge_bridge_drain_answers_inflight_then_refuses():
    """Drain under load at the bridge: a frame in flight when drain
    begins is ANSWERED (no in-flight frame loss), the next frame gets
    the GEBR drain code, and new connections are refused."""
    from gubernator_tpu.serve.edge_bridge import (
        DRAIN_FRAME_ID,
        MAGIC_STALE,
        MAGIC_WREQ,
        MAGIC_WRESP,
        EdgeBridge,
    )

    release = asyncio.Event()

    class _SlowInstance:
        async def get_rate_limits(self, reqs, stage_frame=False):
            await release.wait()
            return [RateLimitResp(limit=r.limit, remaining=3)
                    for r in reqs]

    def _witem():
        name, key = b"res", b"dk"
        return (
            struct.pack("<H", len(name)) + name
            + struct.pack("<H", len(key)) + key
            + struct.pack("<qqqBB", 1, 9, 60000, 0, 0)
        )

    def _wframe(frame_id):
        payload = _witem()
        return (
            struct.pack("<II", MAGIC_WREQ, 1)
            + struct.pack("<IQ", frame_id, 0)
            + struct.pack("<I", len(payload))
            + payload
        )

    async def run():
        path = "/tmp/guber-bridge-drain-test.sock"
        bridge = EdgeBridge(_SlowInstance(), path)
        await bridge.start()
        reader, writer = await asyncio.open_unix_connection(path)
        # consume hello
        magic, flags, rhash, n = struct.unpack(
            "<IIII", await reader.readexactly(16)
        )
        assert n == 0
        # frame 7 starts serving, parked on `release`
        writer.write(_wframe(7))
        await writer.drain()
        while bridge._active_frames == 0:
            await asyncio.sleep(0.005)
        # drain begins with frame 7 in flight
        drain_task = asyncio.ensure_future(bridge.drain(5.0))
        await asyncio.sleep(0.02)
        # frame 8 arrives during the drain: must be refused AFTER 7
        # completes
        writer.write(_wframe(8))
        await writer.drain()
        await asyncio.sleep(0.02)
        release.set()
        # response for 7 first (it was in flight), then the drain GEBR
        magic, n = struct.unpack("<II", await reader.readexactly(8))
        assert magic == MAGIC_WRESP and n == 1
        (fid,) = struct.unpack("<I", await reader.readexactly(4))
        assert fid == 7
        body = await reader.readexactly(n * 29)
        status, limit, remaining, reset = struct.unpack_from(
            "<Bqqq", body
        )
        assert remaining == 3
        magic, fid = struct.unpack("<II", await reader.readexactly(8))
        assert magic == MAGIC_STALE and fid == DRAIN_FRAME_ID
        await asyncio.wait_for(drain_task, 5)
        # new connections are refused while draining
        with pytest.raises((ConnectionError, asyncio.IncompleteReadError)):
            r2, w2 = await asyncio.open_unix_connection(path)
            await r2.readexactly(16)
        await bridge.stop()

    asyncio.run(run())
