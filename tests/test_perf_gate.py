"""Perf-gate tests (r12, scripts/perf_gate.py + PERF_GATE_BASELINE.json).

Tier-1 part: the gate's comparison logic and the committed baseline
manifest's integrity (every guarded workload names an existing BENCH_*
artifact — the manifest is the map from gate workloads to the wins
they guard).

Slow part (deselected from tier-1): the gate end to end on the real
serving stack — it must PASS against a baseline it just measured, and
provably FAIL when a real per-frame delay is injected into the guarded
feature paths (`--inject-frame-ms`, the r8 fault injector).
"""

import importlib.util
import json
import os
import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _gate_module():
    spec = importlib.util.spec_from_file_location(
        "perf_gate", ROOT / "scripts" / "perf_gate.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_evaluate_gate_logic():
    ev = _gate_module().evaluate_gate
    baseline = {
        "workloads": {
            "a": {"committed": 2.0, "artifact": "X.json"},
            "b": {"committed": 1.0, "artifact": "Y.json"},
        }
    }
    # within threshold: pass (a dropped 5%, b improved)
    ok, rows = ev(baseline, {"a": 1.9, "b": 1.2}, 0.10)
    assert ok, rows
    # >10% regression on one workload: fail, and the row says which
    ok, rows = ev(baseline, {"a": 1.7, "b": 1.2}, 0.10)
    assert not ok
    bad = [r for r in rows if r["status"] == "FAIL"]
    assert [r["workload"] for r in bad] == ["a"]
    assert bad[0]["floor"] == pytest.approx(1.8)
    # exactly at the floor: pass (fail is strictly below)
    ok, _ = ev(baseline, {"a": 1.8, "b": 0.9}, 0.10)
    assert ok
    # a workload the gate stopped measuring must FAIL, not skip
    ok, rows = ev(baseline, {"a": 2.0}, 0.10)
    assert not ok
    assert any(
        r["workload"] == "b" and r["status"] == "FAIL" for r in rows
    )
    # a measured-but-unguarded workload is reported, never fails
    ok, rows = ev(baseline, {"a": 2.0, "b": 1.0, "new": 0.1}, 0.10)
    assert ok
    assert any(r["status"] == "unguarded" for r in rows)


def test_baseline_manifest_guards_the_committed_artifacts():
    manifest = json.loads(
        (ROOT / "PERF_GATE_BASELINE.json").read_text()
    )
    assert manifest["schema"] == "perf_gate_baseline_r12"
    wl = manifest["workloads"]
    # the interior wins (incl. the r13 sketch pair) + the two
    # public-door ratios are guarded
    for name in (
        "shed_r10", "submit_r9", "stages_r7", "sketch_r13",
        "shard_r14",
        "frontdoor_geb_over_grpc", "frontdoor_http_over_grpc",
    ):
        assert name in wl, f"workload {name} missing from the manifest"
        entry = wl[name]
        assert (ROOT / entry["artifact"]).exists(), (
            f"{name} cites a non-committed artifact "
            f"{entry['artifact']}"
        )
        assert entry["committed"] > 0
    # the acceptance headline is durable: the committed GEB-over-gRPC
    # paired ratio stays >= 2.5x even at the gate's failure floor
    assert wl["frontdoor_geb_over_grpc"]["committed"] * (
        1 - manifest["threshold_default"]
    ) >= 2.5


def test_frontdoor_artifact_headline():
    doc = json.loads((ROOT / "BENCH_FRONTDOOR_r12.json").read_text())
    assert doc["schema"] == "bench_frontdoor_r12"
    assert doc["acceptance"]["met"] is True
    assert doc["paired"]["geb_over_grpc"]["median"] >= 2.5
    assert doc["gate"]["passed"] is True
    lad = doc["ladder_median_decisions_per_sec"]
    assert lad["geb"] > lad["grpc"]
    assert lad["http"] > lad["grpc"]


@pytest.mark.slow
def test_perf_gate_end_to_end_and_injected_slowdown():
    """The full gate on the real stack, small settings: (1) measure a
    fresh baseline; (2) a clean run against it PASSES; (3) a run with
    a real injected per-frame delay in the guarded paths FAILS."""
    env = dict(os.environ, PYTHONPATH=str(ROOT), JAX_PLATFORMS="cpu")
    base = "/tmp/guber-perf-gate-test-baseline.json"
    art = "/tmp/guber-perf-gate-test-front.json"

    def run(*extra):
        return subprocess.run(
            [
                sys.executable, "scripts/perf_gate.py",
                "--seconds", "1", "--rounds", "2",
                "--device-batch-limit", "1024",
                "--concurrency", "8",
                "--baseline", base, *extra,
            ],
            cwd=ROOT, env=env, capture_output=True, text=True,
            timeout=580,
        )

    r = run("--update-baseline")
    assert r.returncode == 0, r.stderr[-3000:]

    # clean run: generous threshold (short rounds are noisier than the
    # shipped settings; the mechanism, not the margin, is under test)
    r = run("--threshold", "0.5", "--json", art)
    assert r.returncode == 0, r.stderr[-3000:]
    doc = json.loads(pathlib.Path(art).read_text())
    assert doc["gate"]["passed"] is True

    # injected regression: a real per-frame delay in the guarded
    # feature paths. Pipelining absorbs small delays (frames sleep
    # concurrently), so the self-test injects one far past the
    # absorption bound — the paired ratios must collapse below any
    # threshold and the gate has to fail loudly
    r = run("--threshold", "0.5", "--inject-frame-ms", "1000")
    assert r.returncode == 1, (
        f"gate passed despite the injected slowdown:\n{r.stderr[-3000:]}"
    )
    assert "FAIL" in r.stderr
