"""GEB client protocol tests (r12, gubernator_tpu.client_geb).

The client speaks the bridge wire protocol from outside the serving
tier, so its constants are deliberate duplicates — pinned equal here —
and its behavior is tested against the REAL frame-service core
(serve/edge_bridge.py FrameService/GebListener) over real sockets with
fake instances, the test_edge_bridge pattern.
"""

import asyncio
import struct
from dataclasses import dataclass

import pytest

from _util import free_ports
from gubernator_tpu.api.types import RateLimitResp, Status
from gubernator_tpu.serve.edge_bridge import EdgeBridge, GebListener


@dataclass
class FakePeer:
    host: str
    is_owner: bool = False


class _FakeBackendArrays:
    decide_submit_arrays = object()
    decide_submit = object()


class _FakeTraffic:
    def observe_hashes(self, h):
        pass

    def observe(self, keys, hashes):
        pass


class _FakePicker:
    def __init__(self, hosts=("127.0.0.1:81",)):
        self._hosts = list(hosts)

    def peers(self):
        return [
            FakePeer(h, is_owner=(i == 0))
            for i, h in enumerate(self._hosts)
        ]


def _reqs(n=3, prefix="k", limit=5, hits=1):
    from gubernator_tpu.api.types import RateLimitReq

    return [
        RateLimitReq(
            name="geb",
            unique_key=f"{prefix}{i}",
            hits=hits,
            limit=limit,
            duration=60_000,
        )
        for i in range(n)
    ]


def test_wire_constants_match_bridge():
    """client_geb must not import the serving tier, so its wire
    constants are duplicates — this pin is what makes that safe."""
    import gubernator_tpu.client_geb as cg
    import gubernator_tpu.serve.edge_bridge as eb

    for name in (
        "MAGIC_REQ", "MAGIC_RESP", "MAGIC_HELLO", "MAGIC_FAST_REQ",
        "MAGIC_FAST_RESP", "MAGIC_STALE", "MAGIC_WREQ", "MAGIC_WRESP",
        "MAGIC_WFAST_REQ", "MAGIC_WFAST_RESP", "HELLO_FAST",
        "HELLO_WINDOWED", "HELLO_XXH64", "DRAIN_FRAME_ID",
        "MAX_FRAME_PAYLOAD",
    ):
        assert getattr(cg, name) == getattr(eb, name), name
    from gubernator_tpu.serve.server import GEB_CONTENT_TYPE

    assert cg.GEB_CONTENT_TYPE == GEB_CONTENT_TYPE


def test_client_geb_imports_without_jax():
    """The GEB client is a packaged client like client.py: importing it
    must not drag JAX in (subprocess so the rest of the suite can't
    contaminate the check)."""
    import subprocess
    import sys

    r = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys\n"
            "import gubernator_tpu.client_geb as cg\n"
            "banned = [m for m in sys.modules if m == 'jax' "
            "or m.startswith('jax.') or m == 'jaxlib' "
            "or m.startswith('jaxlib.')]\n"
            "assert not banned, banned\n"
            "cg.client_hash_batch(['a_b'])  # hashing path is JAX-free too\n"
            "assert not [m for m in sys.modules if m.startswith('jax')]\n"
            "print('OK')\n",
        ],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_client_hash_matches_store_hash():
    """Fast frames only work if the client's hash equals the store's
    (core.hashing.slot_hash_batch) — same implementation tier, same
    bytes. In-process the tiers always match, so equality must be
    exact."""
    import numpy as np

    from gubernator_tpu.client_geb import (
        client_hash_batch,
        client_hash_is_native,
    )
    from gubernator_tpu.core.hashing import (
        slot_hash_batch,
        using_native_hash,
    )

    assert client_hash_is_native() == using_native_hash()
    keys = [f"geb_k{i}" for i in range(50)] + ["a_b", "x_" + "y" * 300]
    assert np.array_equal(client_hash_batch(keys), slot_hash_batch(keys))


class _ObjectInstance:
    """String-path fake: serves request objects, echoing limit-hits."""

    def __init__(self):
        self.calls = []

    async def get_rate_limits(self, reqs, stage_frame=False):
        self.calls.append([r.unique_key for r in reqs])
        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT,
                limit=r.limit,
                remaining=r.limit - r.hits,
                reset_time=42,
            )
            for r in reqs
        ]


class _ArrayInstance:
    """Array-path fake: echoes limit back as remaining so the fast
    path's ordering is checkable."""

    backend = _FakeBackendArrays()
    traffic = _FakeTraffic()

    def __init__(self, hosts=("127.0.0.1:81",)):
        import numpy as np

        self.picker = _FakePicker(hosts)
        outer = self

        class B:
            async def decide_arrays(self, fields, frame=True):
                n = fields["key_hash"].shape[0]
                outer.seen = outer.__dict__.setdefault("seen", [])
                outer.seen.append(n)
                return (
                    np.zeros(n, np.int64),
                    fields["limit"],
                    fields["limit"],
                    np.full(n, 7, np.int64),
                )

        self.batcher = B()


def _with_listener(instance, coro_fn, window=0):
    """Run `coro_fn(port)` against a GebListener over `instance`."""

    async def run():
        (port,) = free_ports(1)
        lst = GebListener(
            instance, f"127.0.0.1:{port}", window=window
        )
        await lst.start()
        try:
            return await coro_fn(port, lst)
        finally:
            await lst.stop()

    return asyncio.run(run())


def test_string_mode_roundtrip_and_negotiation():
    inst = _ObjectInstance()

    async def go(port, lst):
        from gubernator_tpu.client_geb import AsyncGebClient

        async with AsyncGebClient(
            f"127.0.0.1:{port}", mode="string"
        ) as c:
            assert c.hello.windowed
            assert not c._use_fast
            out = await c.get_rate_limits(_reqs(4))
            return out

    out = _with_listener(inst, go)
    assert [
        (int(r.status), r.limit, r.remaining, r.reset_time) for r in out
    ] == [(0, 5, 4, 42)] * 4
    assert inst.calls == [["k0", "k1", "k2", "k3"]]


def test_chain_frames_roundtrip_and_capability_gate():
    """Quota chains over the socket door (r15): a mixed plain/chained
    batch rides ONE GEBC frame — the chain levels arrive intact at the
    instance, fast framing is bypassed, responses come back in order —
    and a server hello without HELLO_CHAIN is refused client-side
    before anything hits the wire."""
    from gubernator_tpu.api.types import ChainLevel

    inst = _ObjectInstance()
    seen_chains = []
    orig = inst.get_rate_limits

    async def capture(reqs, stage_frame=False):
        seen_chains.append(
            [[(lv.unique_key, lv.limit, lv.duration) for lv in r.chain]
             for r in reqs]
        )
        return await orig(reqs, stage_frame)

    inst.get_rate_limits = capture

    async def go(port, lst):
        from gubernator_tpu.client_geb import (
            HELLO_CHAIN,
            AsyncGebClient,
            GebError,
        )

        async with AsyncGebClient(f"127.0.0.1:{port}") as c:
            assert c.hello.chain, hex(c.hello.flags)
            reqs = _reqs(3)
            reqs[1].chain = [
                ChainLevel("global", 100, 0),
                ChainLevel("tenant:a", 10, 2000),
            ]
            out = await c.get_rate_limits(reqs)
            assert len(out) == 3
            # a pre-r15 hello (no chain capability) refuses client-side
            c.hello.flags &= ~HELLO_CHAIN
            try:
                await c.get_rate_limits(reqs)
            except GebError as e:
                assert "HELLO_CHAIN" in str(e)
            else:
                raise AssertionError("expected GebError")
        return out

    _with_listener(inst, go)
    assert seen_chains == [[
        [],
        [("global", 100, 0), ("tenant:a", 10, 2000)],
        [],
    ]]


def test_hello_chain_bit_follows_kill_switch():
    """With GUBER_CHAINS=0 the hello must NOT advertise HELLO_CHAIN,
    so a chained caller fails fast client-side instead of shipping
    GEBC frames destined for per-item refusal (review finding)."""
    from types import SimpleNamespace

    from gubernator_tpu.api.types import ChainLevel

    inst = _ObjectInstance()
    inst.conf = SimpleNamespace(chains=False)

    async def go(port, lst):
        from gubernator_tpu.client_geb import AsyncGebClient, GebError

        async with AsyncGebClient(f"127.0.0.1:{port}") as c:
            assert not c.hello.chain, hex(c.hello.flags)
            reqs = _reqs(1)
            reqs[0].chain = [ChainLevel("g", 5, 0)]
            try:
                await c.get_rate_limits(reqs)
            except GebError as e:
                assert "HELLO_CHAIN" in str(e)
            else:
                raise AssertionError("expected GebError")
            # plain traffic is unaffected
            out = await c.get_rate_limits(_reqs(2))
            assert len(out) == 2

    _with_listener(inst, go)


def test_auto_mode_uses_fast_on_single_node():
    """In-process, the client and 'store' share a hash tier, the ring
    is single-node, and the fake backend takes arrays — auto must pick
    fast framing and the responses must come back in order."""
    inst = _ArrayInstance()

    async def go(port, lst):
        from gubernator_tpu.client_geb import AsyncGebClient

        async with AsyncGebClient(f"127.0.0.1:{port}") as c:
            assert c._use_fast, hex(c.hello.flags)
            reqs = _reqs(5)
            for i, r in enumerate(reqs):
                r.limit = 100 + i
            out = await c.get_rate_limits(reqs)
            return out

    out = _with_listener(inst, go)
    # fast echo: remaining == limit, reset from the fake batcher
    assert [(r.remaining, r.reset_time) for r in out] == [
        (100 + i, 7) for i in range(5)
    ]


def test_auto_mode_downgrades_to_string_on_multinode():
    """Fast frames bypass instance routing, so auto mode must refuse
    them on a multi-node ring (string framing keeps forwarding
    semantics); the object path serves instead."""
    import numpy as np  # noqa: F401

    class Both(_ObjectInstance):
        backend = _FakeBackendArrays()
        traffic = _FakeTraffic()
        picker = _FakePicker(["10.0.0.1:81", "10.0.0.2:81"])

    inst = Both()

    async def go(port, lst):
        from gubernator_tpu.client_geb import AsyncGebClient

        async with AsyncGebClient(f"127.0.0.1:{port}") as c:
            assert not c._use_fast
            return await c.get_rate_limits(_reqs(2))

    out = _with_listener(inst, go)
    assert len(out) == 2 and inst.calls


def test_global_items_ride_string_frames_even_in_fast_mode():
    """A batch carrying GLOBAL/NO_BATCHING behaviors cannot be encoded
    as fast records — the client must fall back to string framing for
    that batch (auto mode, fast otherwise negotiated)."""
    from gubernator_tpu.api.types import Behavior

    class Both(_ArrayInstance, _ObjectInstance):
        def __init__(self):
            _ArrayInstance.__init__(self)
            _ObjectInstance.__init__(self)

    inst = Both()

    async def go(port, lst):
        from gubernator_tpu.client_geb import AsyncGebClient

        async with AsyncGebClient(f"127.0.0.1:{port}") as c:
            assert c._use_fast
            reqs = _reqs(3)
            reqs[1].behavior = Behavior.GLOBAL
            return await c.get_rate_limits(reqs)

    out = _with_listener(inst, go)
    assert len(out) == 3
    # the GLOBAL batch went through the object path (string frame)
    assert inst.calls and inst.calls[0] == ["k0", "k1", "k2"]


def test_out_of_order_completion_pipelines():
    """Two concurrent calls on one connection: the slow frame must not
    convoy the fast one (out-of-order completion by frame id), and
    both must resolve with their OWN batch's responses."""
    release = asyncio.Event()

    class Inst:
        async def get_rate_limits(self, reqs, stage_frame=False):
            if reqs[0].unique_key == "slow":
                await release.wait()
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT,
                    limit=r.limit,
                    remaining=len(r.unique_key),
                    reset_time=1,
                )
                for r in reqs
            ]

    async def go(port, lst):
        from gubernator_tpu.api.types import RateLimitReq
        from gubernator_tpu.client_geb import AsyncGebClient

        async with AsyncGebClient(
            f"127.0.0.1:{port}", mode="string"
        ) as c:
            slow = asyncio.ensure_future(
                c.get_rate_limits(
                    [RateLimitReq(name="n", unique_key="slow", hits=1,
                                  limit=5, duration=1000)]
                )
            )
            await asyncio.sleep(0.05)
            fast = await c.get_rate_limits(
                [RateLimitReq(name="n", unique_key="quick!", hits=1,
                              limit=5, duration=1000)]
            )
            assert not slow.done()  # still parked behind the gate
            release.set()
            return fast, await slow

    fast, slow = _with_listener(Inst(), go)
    assert fast[0].remaining == len("quick!")
    assert slow[0].remaining == len("slow")


def test_window_negotiation_caps_client_side():
    inst = _ObjectInstance()

    async def go(port, lst):
        from gubernator_tpu.client_geb import AsyncGebClient

        async with AsyncGebClient(
            f"127.0.0.1:{port}", mode="string", window=2
        ) as c:
            assert c.hello.window == lst.window >= 2
            assert c._window == 2  # min(server, requested)
            return True

    assert _with_listener(inst, go)


def test_stale_ring_fails_frame_and_reconnect_heals():
    """A ring change between hello and frame must surface as
    GebStaleRingError (the frame was NOT served), and the next call
    must transparently reconnect onto the fresh ring and succeed."""
    inst = _ArrayInstance()

    async def go(port, lst):
        from gubernator_tpu.client_geb import (
            AsyncGebClient,
            GebStaleRingError,
        )

        c = AsyncGebClient(f"127.0.0.1:{port}")
        await c.connect()
        assert c._use_fast
        # membership changes AFTER the hello: new picker object, new
        # fingerprint — the client's next fast frame is now stale
        inst.picker = _FakePicker(["127.0.0.1:82"])
        with pytest.raises(GebStaleRingError):
            await c.get_rate_limits(_reqs(2))
        out = await c.get_rate_limits(_reqs(2))  # reconnect re-hellos
        await c.close()
        return out

    out = _with_listener(inst, go)
    assert len(out) == 2


def test_drain_refusal_surfaces_and_names_retry_safety():
    inst = _ObjectInstance()

    async def go(port, lst):
        from gubernator_tpu.client_geb import (
            AsyncGebClient,
            GebDrainingError,
        )

        c = AsyncGebClient(f"127.0.0.1:{port}", mode="string")
        await c.connect()
        await lst.drain(0.5)
        with pytest.raises(GebDrainingError):
            await c.get_rate_limits(_reqs(1))
        await c.close()
        return True

    assert _with_listener(inst, go)


def test_sync_client_roundtrip_and_pipelined():
    inst = _ObjectInstance()

    async def hold(port, lst):
        # keep the listener alive while the BLOCKING client (own loop
        # thread) drives it
        from gubernator_tpu.client_geb import GebClient

        def blocking():
            with GebClient(
                f"127.0.0.1:{port}", mode="string"
            ) as c:
                one = c.get_rate_limits(_reqs(2))
                many = c.get_rate_limits_pipelined(
                    [_reqs(1), _reqs(3)]
                )
                return one, many

        return await asyncio.to_thread(blocking)

    one, many = _with_listener(inst, hold)
    assert len(one) == 2
    assert [len(b) for b in many] == [1, 3]


def test_client_against_edge_bridge_unix_socket():
    """The same client speaks to a bridge unix socket (the co-located
    deployment shape) — endpoint parsing picks the unix transport from
    the path spec."""
    inst = _ObjectInstance()

    async def run():
        path = "/tmp/guber-geb-client-bridge.sock"
        bridge = EdgeBridge(inst, path)
        await bridge.start()
        try:
            from gubernator_tpu.client_geb import AsyncGebClient

            async with AsyncGebClient(path, mode="string") as c:
                return await c.get_rate_limits(_reqs(2))
        finally:
            await bridge.stop()

    out = asyncio.run(run())
    assert [r.reset_time for r in out] == [42, 42]


def test_http_binary_door_content_type_and_roundtrip():
    """POST /v1/geb end to end against a real gateway (exact backend):
    hello on GET, string-frame round trip, content-type gate, and
    frame-level malformed input as 400 — no protobuf, no JSON."""
    import json
    import urllib.error
    import urllib.request

    from gubernator_tpu.client_geb import (
        GEB_CONTENT_TYPE,
        build_frame,
        decode_string_body,
        parse_hello_bytes,
    )
    from gubernator_tpu.cluster import LocalCluster
    from gubernator_tpu.serve.backends import ExactBackend

    g, h = free_ports(2)
    base = f"http://127.0.0.1:{h}"
    c = LocalCluster(
        [f"127.0.0.1:{g}"],
        backend_factory=lambda: ExactBackend(10_000),
        http_addresses=[f"127.0.0.1:{h}"],
    )
    c.start()
    try:
        with urllib.request.urlopen(base + "/v1/geb", timeout=10) as r:
            hello = parse_hello_bytes(r.read())
        assert hello.windowed and len(hello.nodes) == 1

        frame, is_fast = build_frame(
            _reqs(3), fast=False, windowed=False
        )
        req = urllib.request.Request(
            base + "/v1/geb", frame,
            {"Content-Type": GEB_CONTENT_TYPE},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = r.read()
        magic, n = struct.unpack_from("<II", body, 0)
        out = decode_string_body(body[8:], n)
        assert [x.remaining for x in out] == [4, 4, 4]

        # >1 MiB LEGAL frame: aiohttp's default client_max_size (1
        # MiB) would 413 this before the handler runs — the door must
        # size its body bound to the max legal GEB frame instead
        from gubernator_tpu.api.types import RateLimitReq

        big_reqs = [
            RateLimitReq(
                name="api", unique_key="K" * 50_000 + str(i),
                hits=1, limit=5, duration=60_000,
            )
            for i in range(24)
        ]
        frame, _ = build_frame(big_reqs, fast=False, windowed=False)
        assert len(frame) > (1 << 20)
        req = urllib.request.Request(
            base + "/v1/geb", frame,
            {"Content-Type": GEB_CONTENT_TYPE},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = r.read()
        magic, n = struct.unpack_from("<II", body, 0)
        assert n == 24
        out = decode_string_body(body[8:], n)
        assert [x.remaining for x in out] == [4] * 24

        # past the payload bound: 413 from the door's own cap (the
        # app-wide client_max_size stays at the JSON routes' 1 MiB)
        req = urllib.request.Request(
            base + "/v1/geb", b"\x00" * ((8 << 20) + 128),
            {"Content-Type": GEB_CONTENT_TYPE},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 413

        # wrong content type: a clear 415, never a frame decode
        req = urllib.request.Request(
            base + "/v1/geb", b'{"requests": []}',
            {"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 415

        # malformed frames: 400 with a JSON error, not a 500 — incl.
        # a GEB1 frame whose plen is self-consistent but whose item
        # count lies (payload holds 1 item, header says 2: the
        # truncated-varlen shape that surfaces as struct.error)
        one_item = (
            struct.pack("<H", 3) + b"api" + struct.pack("<H", 1) + b"k"
            + struct.pack("<qqqBB", 1, 5, 1000, 0, 0)
        )
        lying_count = (
            struct.pack("<II", 0x31424547, 2)
            + struct.pack("<I", len(one_item)) + one_item
        )
        for payload in (
            b"", b"\x00" * 7, b"GARBAGE-",
            struct.pack("<II", 0x31424547, 5) + b"\x01\x02",
            frame[:-3],
            lying_count,
        ):
            req = urllib.request.Request(
                base + "/v1/geb", payload,
                {"Content-Type": GEB_CONTENT_TYPE},
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 400, payload
            assert "error" in json.loads(e.value.read())
    finally:
        c.stop()


def test_unknown_status_byte_fails_loudly():
    """A corrupted or future-version status byte must raise GebError —
    never decode fail-open as UNDER_LIMIT while every other malformed
    field in the module fails loudly."""
    import gubernator_tpu.client_geb as cg

    bad_fast = struct.pack("<Bqqq", 7, 5, 4, 1)
    with pytest.raises(cg.GebError, match="status"):
        cg.decode_fast_body(bad_fast, 1)
    bad_string = bad_fast + struct.pack("<H", 0) + struct.pack("<H", 0)
    with pytest.raises(cg.GebError, match="status"):
        cg.decode_string_body(bad_string, 1)


def test_wire_count_bound_mirrors_server():
    """A server-supplied response count beyond the frame bound raises
    before sizing a read from it — the client-side mirror of the
    server's lying-length defense."""
    import gubernator_tpu.client_geb as cg

    assert cg._check_wire_count(5) == 5
    with pytest.raises(cg.GebError, match="item count"):
        cg._check_wire_count(cg.MAX_FRAME_ITEMS + 1)


def test_oversized_payload_refused_client_side():
    """A string frame whose payload would cross MAX_FRAME_PAYLOAD is
    refused loudly before the wire — the server's read-side bound
    kills the connection for anything larger."""
    import gubernator_tpu.client_geb as cg
    from gubernator_tpu.api.types import RateLimitReq

    reqs = [
        RateLimitReq(
            name="n", unique_key="K" * 60_000, hits=1, limit=5,
            duration=60_000,
        )
        for _ in range(150)
    ]
    with pytest.raises(cg.GebError, match="payload"):
        cg.build_frame(reqs, fast=False, windowed=False)


def test_http_client_short_body_raises_geberror():
    """A truncating proxy or an empty 200 body surfaces as GebError
    (the module's contract), not a raw struct.error."""
    import gubernator_tpu.client_geb as cg
    from aiohttp import web

    async def run():
        (port,) = free_ports(1)

        async def hello(request):
            return web.Response(
                body=struct.pack("<IIII", cg.MAGIC_HELLO, 0, 0, 0),
                content_type=cg.GEB_CONTENT_TYPE,
            )

        async def post(request):
            return web.Response(
                body=b"\x01", content_type=cg.GEB_CONTENT_TYPE
            )

        app = web.Application()
        app.router.add_get("/v1/geb", hello)
        app.router.add_post("/v1/geb", post)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        try:
            c = cg.AsyncHttpGebClient(f"http://127.0.0.1:{port}")
            with pytest.raises(cg.GebError, match="short response"):
                await c.get_rate_limits(_reqs(1))
            await c.close()
        finally:
            await runner.cleanup()

    asyncio.run(run())
