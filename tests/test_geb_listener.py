"""Daemon GEB listener (GUBER_GEB_PORT, r12): config knobs + hostile-
frame fuzz.

The GEB door is the first CLIENT-facing surface speaking the binary
frame protocol (the bridge only ever faced the trusted edge binary),
so it gets the hostile-input treatment the edge's parsers get from the
ASan suites: seeded garbage, truncated frames, lying length fields,
and desynced streams must at worst close the offending connection —
never crash the daemon, never hang the read loop, never poison OTHER
connections.
"""

import asyncio
import os
import struct

import numpy as np
import pytest

from _util import free_ports
from gubernator_tpu.api.types import RateLimitResp, Status
from gubernator_tpu.serve.edge_bridge import (
    MAGIC_FAST_REQ,
    MAGIC_REQ,
    MAGIC_WFAST_REQ,
    MAGIC_WREQ,
    MAX_FRAME_PAYLOAD,
    GebListener,
)


# -- config knobs -----------------------------------------------------------


def test_geb_port_knobs_parse_and_validate():
    from gubernator_tpu.serve.config import config_from_env

    conf = config_from_env(
        {"GUBER_GEB_PORT": "9470", "GUBER_GEB_WINDOW": "8"}
    )
    assert conf.geb_port == 9470
    assert conf.geb_window == 8
    assert config_from_env({}).geb_port == 0  # off by default

    with pytest.raises(ValueError):
        config_from_env({"GUBER_GEB_PORT": "70000"})
    with pytest.raises(ValueError):
        config_from_env({"GUBER_GEB_WINDOW": "-1"})

    # trusted-door payload cap (the client doors bound at 8 MiB fixed)
    assert config_from_env({}).edge_max_frame_mib == 256
    assert config_from_env(
        {"GUBER_EDGE_MAX_FRAME_MIB": "512"}
    ).edge_max_frame_mib == 512
    with pytest.raises(ValueError):
        config_from_env({"GUBER_EDGE_MAX_FRAME_MIB": "0"})


def test_geb_listener_refuses_ipv6_address():
    with pytest.raises(ValueError):
        GebListener(object(), "[::1]:9470")


# -- hostile-frame fuzz -----------------------------------------------------


class _Instance:
    """Minimal object-path instance; any crash in here would be a test
    bug, not a parser survival."""

    async def get_rate_limits(self, reqs, stage_frame=False):
        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=max(r.limit - r.hits, 0), reset_time=5,
            )
            for r in reqs
        ]


def _item(name: bytes, key: bytes, hits=1, limit=5, duration=1000) -> bytes:
    return (
        struct.pack("<H", len(name)) + name
        + struct.pack("<H", len(key)) + key
        + struct.pack("<qqqBB", hits, limit, duration, 0, 0)
    )


def _good_frame() -> bytes:
    payload = _item(b"api", b"ok")
    return (
        struct.pack("<II", MAGIC_REQ, 1)
        + struct.pack("<I", len(payload))
        + payload
    )


async def _drain_hello(reader):
    magic, flags, rhash, n = struct.unpack(
        "<IIII", await reader.readexactly(16)
    )
    for _ in range(n):
        _s, glen = struct.unpack("<BH", await reader.readexactly(3))
        await reader.readexactly(glen)
        (blen,) = struct.unpack("<H", await reader.readexactly(2))
        await reader.readexactly(blen)
    return rhash


def _hostile_corpus(rng, ring_hash):
    """Adversarial frames mirroring the edge ASan corpus's shapes:
    garbage, truncation, lying counts/lengths, desynced payloads."""
    yield b"\x00" * 64  # zero magic + zeros
    yield rng.bytes(256)  # pure noise
    yield struct.pack("<II", 0xDEADBEEF, 10)  # unknown magic
    yield struct.pack("<II", MAGIC_REQ, 1)  # header then EOF
    # string frame: count says 1000, payload is 3 bytes
    yield struct.pack("<II", MAGIC_REQ, 1000) + struct.pack(
        "<I", 3
    ) + b"abc"
    # string frame: name_len runs past the payload
    bad = struct.pack("<H", 500) + b"xx"
    yield struct.pack("<II", MAGIC_REQ, 1) + struct.pack(
        "<I", len(bad)
    ) + bad
    # fast frame: payload not a multiple of the record size
    yield struct.pack("<II", MAGIC_FAST_REQ, 2) + struct.pack(
        "<II", ring_hash, 17
    ) + rng.bytes(17)
    # windowed fast frame with a lying item count
    yield struct.pack("<II", MAGIC_WFAST_REQ, 9999) + struct.pack(
        "<IIQ", 1, ring_hash, 0
    ) + struct.pack("<I", 33) + rng.bytes(33)
    # windowed string frame whose payload is noise
    noise = rng.bytes(64)
    yield struct.pack("<II", MAGIC_WREQ, 3) + struct.pack(
        "<IQ", 2, 0
    ) + struct.pack("<I", len(noise)) + noise
    # invalid UTF-8 name/key (must answer per-item, not crash)
    payload = _item(b"\xff\xfe", b"\x80\x81")
    yield struct.pack("<II", MAGIC_REQ, 1) + struct.pack(
        "<I", len(payload)
    ) + payload
    # lying u32 payload length advertising up to ~4 GiB: must be
    # refused at the header, never buffered toward
    yield struct.pack("<II", MAGIC_REQ, 1) + struct.pack(
        "<I", 0xFFFFFFFF
    )
    yield struct.pack("<II", MAGIC_WREQ, 1) + struct.pack(
        "<IQ", 3, 0
    ) + struct.pack("<I", MAX_FRAME_PAYLOAD + 1)
    yield struct.pack("<II", MAGIC_FAST_REQ, 1) + struct.pack(
        "<II", ring_hash, 0x40000000
    )
    # truncated mid-payload (sender hangs up after half)
    good = _good_frame()
    yield good[: len(good) // 2]


@pytest.mark.parametrize("seed", [1, 2])
def test_hostile_frames_never_kill_the_listener(seed):
    """Every hostile frame at worst closes ITS connection; a
    well-formed frame on a fresh connection is still served after each
    one — the daemon survives the whole corpus."""

    async def run():
        (port,) = free_ports(1)
        lst = GebListener(_Instance(), f"127.0.0.1:{port}")
        await lst.start()
        rng = np.random.default_rng(seed)
        try:
            async def probe_alive():
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                await _drain_hello(reader)
                writer.write(_good_frame())
                await writer.drain()
                magic, n = struct.unpack(
                    "<II",
                    await asyncio.wait_for(reader.readexactly(8), 5),
                )
                body = await asyncio.wait_for(
                    reader.readexactly(29), 5
                )
                writer.close()
                st, limit, rem, reset = struct.unpack_from(
                    "<Bqqq", body, 0
                )
                return (magic, n, st, rem)

            baseline = await probe_alive()
            ring = 0
            for i, frame in enumerate(
                _hostile_corpus(rng, ring_hash=0)
            ):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                ring = await _drain_hello(reader)
                writer.write(frame)
                try:
                    await writer.drain()
                    # the connection must resolve (response or close)
                    # within a bound — EXCEPT a truncated frame, where
                    # waiting for the rest of the bytes is the correct
                    # server behavior (closing our side cleans it up;
                    # the probe below is the health check either way)
                    await asyncio.wait_for(reader.read(4096), 2)
                except (
                    asyncio.TimeoutError, ConnectionError, OSError
                ):
                    pass
                finally:
                    writer.close()
                assert await probe_alive() == baseline, (
                    f"listener unhealthy after hostile frame {i}"
                )
            # interleave: hostile frame on conn A must not poison a
            # CONCURRENT well-formed conn B
            ra, wa = await asyncio.open_connection("127.0.0.1", port)
            await _drain_hello(ra)
            rb, wb = await asyncio.open_connection("127.0.0.1", port)
            await _drain_hello(rb)
            wa.write(struct.pack("<II", 0xBADBAD, 1))
            await wa.drain()
            wb.write(_good_frame())
            await wb.drain()
            magic, n = struct.unpack(
                "<II", await asyncio.wait_for(rb.readexactly(8), 5)
            )
            await rb.readexactly(29)
            wa.close()
            wb.close()
        finally:
            await lst.stop()

    asyncio.run(run())


def test_oversized_payload_length_closes_connection():
    """A frame header advertising a payload beyond MAX_FRAME_PAYLOAD
    must close the connection IMMEDIATELY — not sit buffering toward a
    multi-GiB plen (the remote memory-exhaustion vector on this
    client-facing door). EOF, not a read timeout, is the pin: the old
    behavior blocked waiting for the advertised bytes."""

    async def run():
        (port,) = free_ports(1)
        lst = GebListener(_Instance(), f"127.0.0.1:{port}")
        await lst.start()
        try:
            for hdr in (
                struct.pack("<II", MAGIC_REQ, 1)
                + struct.pack("<I", 0xFFFFFFFF),
                struct.pack("<II", MAGIC_WREQ, 1)
                + struct.pack("<IQ", 3, 0)
                + struct.pack("<I", MAX_FRAME_PAYLOAD + 1),
                struct.pack("<II", MAGIC_FAST_REQ, 1)
                + struct.pack("<II", 0, 0x40000000),
            ):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                await _drain_hello(reader)
                writer.write(hdr)
                await writer.drain()
                data = await asyncio.wait_for(reader.read(4096), 5)
                assert data == b"", hdr[:8]
                writer.close()
        finally:
            await lst.stop()

    asyncio.run(run())


def test_edge_bridge_keeps_headroom_for_large_legal_frames():
    """Per-door payload caps: the client-facing GEB door bounds at
    MAX_FRAME_PAYLOAD, but the trusted edge bridge must keep serving
    legal >8 MiB frames (the compiled edge batches u16-length keys at
    --batch-limit items with no byte bound and no split logic)."""
    import tempfile

    from gubernator_tpu.serve.edge_bridge import (
        EDGE_MAX_FRAME_PAYLOAD,
        EdgeBridge,
    )

    assert EDGE_MAX_FRAME_PAYLOAD > MAX_FRAME_PAYLOAD

    async def run():
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "e.sock")
            br = EdgeBridge(_Instance(), path)
            await br.start()
            try:
                reader, writer = await asyncio.open_unix_connection(
                    path
                )
                await _drain_hello(reader)
                items = b"".join(
                    _item(b"api", b"K" * 60_000 + str(i).encode())
                    for i in range(200)
                )
                assert len(items) > MAX_FRAME_PAYLOAD
                writer.write(
                    struct.pack("<II", MAGIC_REQ, 200)
                    + struct.pack("<I", len(items))
                    + items
                )
                await writer.drain()
                magic, n = struct.unpack(
                    "<II",
                    await asyncio.wait_for(reader.readexactly(8), 15),
                )
                assert n == 200
                writer.close()
            finally:
                await br.stop()

    asyncio.run(run())


def test_random_mutation_fuzz_on_windowed_frames():
    """Byte-mutation fuzz: take well-formed windowed string frames and
    flip random bytes; the listener must survive every mutant (serve,
    per-item-error, or close — never hang, never die)."""

    async def run():
        (port,) = free_ports(1)
        lst = GebListener(_Instance(), f"127.0.0.1:{port}")
        await lst.start()
        rng = np.random.default_rng(7)
        payload = b"".join(
            _item(b"svc", b"key%d" % i) for i in range(4)
        )
        base = (
            struct.pack("<II", MAGIC_WREQ, 4)
            + struct.pack("<IQ", 3, 0)
            + struct.pack("<I", len(payload))
            + payload
        )
        try:
            for trial in range(40):
                frame = bytearray(base)
                for _ in range(int(rng.integers(1, 6))):
                    frame[int(rng.integers(len(frame)))] = int(
                        rng.integers(256)
                    )
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                await _drain_hello(reader)
                writer.write(bytes(frame))
                try:
                    await writer.drain()
                    await asyncio.wait_for(reader.read(8192), 5)
                except (ConnectionError, OSError):
                    pass
                finally:
                    writer.close()
            # still alive and correct afterwards
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port
            )
            await _drain_hello(reader)
            writer.write(_good_frame())
            await writer.drain()
            magic, n = struct.unpack(
                "<II", await asyncio.wait_for(reader.readexactly(8), 5)
            )
            assert n == 1
            writer.close()
        finally:
            await lst.stop()

    asyncio.run(run())


def test_daemon_env_boot_serves_geb_door():
    """GUBER_GEB_PORT through the real daemon boot path (subprocess,
    exact backend): the daemon must open the door and serve the
    packaged client."""
    import pathlib
    import subprocess
    import sys
    import time

    root = pathlib.Path(__file__).resolve().parent.parent
    g, h, geb = free_ports(3)
    env = dict(
        os.environ,
        PYTHONPATH=str(root),
        GUBER_BACKEND="exact",
        GUBER_GRPC_ADDRESS=f"127.0.0.1:{g}",
        GUBER_HTTP_ADDRESS=f"127.0.0.1:{h}",
        GUBER_GEB_PORT=str(geb),
        GUBER_PEERS=f"127.0.0.1:{g}",
    )
    daemon = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=root, env=env,
    )
    try:
        from gubernator_tpu.api.types import RateLimitReq
        from gubernator_tpu.client_geb import GebClient, GebError

        deadline = time.monotonic() + 60
        out = None
        while time.monotonic() < deadline:
            if daemon.poll() is not None:
                pytest.fail(f"daemon died:\n{daemon.stdout.read()}")
            try:
                with GebClient(
                    f"127.0.0.1:{geb}", mode="string", timeout=5
                ) as c:
                    out = c.get_rate_limits(
                        [RateLimitReq(name="boot", unique_key="k",
                                      hits=1, limit=3, duration=1000)]
                    )
                break
            except (GebError, OSError, ConnectionError):
                time.sleep(0.3)
        assert out is not None, "GEB door never came up"
        assert out[0].remaining == 2
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=15)
        except subprocess.TimeoutExpired:
            daemon.kill()
