"""Shared test helpers."""

import socket


def free_ports(n):
    """n distinct ephemeral localhost ports (bind-then-release)."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()
