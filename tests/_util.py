"""Shared test helpers."""

import json
import os
import pathlib
import socket
import time
import urllib.error
import urllib.request


def post_json(url, body, timeout=30.0, retries=8, backoff=0.25):
    """POST a JSON body and decode the JSON response, with BOUNDED
    retry on transient 503s (r15 deflake of the r14 note: the
    edge-cluster suites could 503-flake under full-suite load on one
    core while passing in isolation).

    Retrying a 503 is safe by protocol contract: the edge/daemon doors
    answer 503 only for frames REFUSED un-served (lane down, shard
    connect failure, conn cap — the HTTP face of the GEBR refusal,
    whose client contract is explicitly retry-safe), so no hit can be
    double-charged. Connection-refused/reset during setup is equally
    un-served and retried. TIMEOUTS ARE NOT RETRIED — an expired
    in-flight request's delivery is unknown and a retry could double
    charge; a wedged fixture should fail loudly, not double-count.
    """
    data = json.dumps(body).encode()
    last = None
    for attempt in range(retries + 1):
        try:
            return json.loads(
                urllib.request.urlopen(
                    urllib.request.Request(
                        url,
                        data=data,
                        headers={"Content-Type": "application/json"},
                    ),
                    timeout=timeout,
                ).read()
            )
        except urllib.error.HTTPError as e:
            if e.code != 503:
                raise
            last = e
        except urllib.error.URLError as e:
            if not isinstance(
                e.reason, (ConnectionRefusedError, ConnectionResetError)
            ):
                raise
            last = e
        except (ConnectionRefusedError, ConnectionResetError) as e:
            last = e
        time.sleep(backoff * (attempt + 1))
    raise last


def edge_binary() -> "pathlib.Path":
    """Path to the guber-edge binary the edge suites drive. Overridable
    via GUBER_EDGE_BIN so the same suites can run against the
    ASan/UBSan build (tests/test_edge_asan.py)."""
    override = os.environ.get("GUBER_EDGE_BIN")
    if override:
        return pathlib.Path(override)
    root = pathlib.Path(__file__).resolve().parent.parent
    return root / "gubernator_tpu" / "native" / "edge" / "guber-edge"


def free_ports(n):
    """n distinct ephemeral localhost ports (bind-then-release)."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def spawn_daemon_edge(
    env_overrides: dict,
    sock_path: str,
    edge_http: int,
    edge_grpc: int = 0,
    daemon_boot_timeout: float = 180.0,
):
    """Spawn a daemon (edge socket enabled) plus a guber-edge fronting
    it, with HARD readiness checks: a dead or never-listening process
    fails with its captured output instead of leaking into the tests as
    opaque connection-refused noise. Returns (daemon, edge) Popens; the
    caller owns teardown (edge.kill(); daemon.terminate()).

    Shared across the daemon+edge e2e suites so spawn/teardown fixes
    land once (r4 review: three divergent copies had already drifted).
    """
    import os
    import pathlib
    import subprocess
    import sys
    import time

    import pytest

    root = pathlib.Path(__file__).resolve().parent.parent
    edge_bin = edge_binary()
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    env = dict(os.environ, PYTHONPATH=str(root), **env_overrides)
    daemon = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=root, env=env,
    )
    deadline = time.monotonic() + daemon_boot_timeout
    while time.monotonic() < deadline and not os.path.exists(sock_path):
        time.sleep(0.2)
        if daemon.poll() is not None:
            pytest.fail(f"daemon died:\n{daemon.stdout.read()}")
    if not os.path.exists(sock_path):
        daemon.kill()
        pytest.fail("daemon never created the edge socket")

    args = [str(edge_bin), "--listen", str(edge_http),
            "--backend", sock_path]
    if edge_grpc:
        args += ["--grpc-listen", str(edge_grpc)]
    edge = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    probe_port = edge_grpc or edge_http
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if edge.poll() is not None:
            daemon.kill()
            pytest.fail(f"edge died:\n{edge.stdout.read()}")
        try:
            socket.create_connection(
                ("127.0.0.1", probe_port), timeout=1
            ).close()
            return daemon, edge
        except OSError:
            time.sleep(0.05)
    edge.kill()
    daemon.kill()
    pytest.fail("edge never started listening")
