"""Shared test helpers."""

import os
import pathlib
import socket


def edge_binary() -> "pathlib.Path":
    """Path to the guber-edge binary the edge suites drive. Overridable
    via GUBER_EDGE_BIN so the same suites can run against the
    ASan/UBSan build (tests/test_edge_asan.py)."""
    override = os.environ.get("GUBER_EDGE_BIN")
    if override:
        return pathlib.Path(override)
    root = pathlib.Path(__file__).resolve().parent.parent
    return root / "gubernator_tpu" / "native" / "edge" / "guber-edge"


def free_ports(n):
    """n distinct ephemeral localhost ports (bind-then-release)."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def spawn_daemon_edge(
    env_overrides: dict,
    sock_path: str,
    edge_http: int,
    edge_grpc: int = 0,
    daemon_boot_timeout: float = 180.0,
):
    """Spawn a daemon (edge socket enabled) plus a guber-edge fronting
    it, with HARD readiness checks: a dead or never-listening process
    fails with its captured output instead of leaking into the tests as
    opaque connection-refused noise. Returns (daemon, edge) Popens; the
    caller owns teardown (edge.kill(); daemon.terminate()).

    Shared across the daemon+edge e2e suites so spawn/teardown fixes
    land once (r4 review: three divergent copies had already drifted).
    """
    import os
    import pathlib
    import subprocess
    import sys
    import time

    import pytest

    root = pathlib.Path(__file__).resolve().parent.parent
    edge_bin = edge_binary()
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    env = dict(os.environ, PYTHONPATH=str(root), **env_overrides)
    daemon = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=root, env=env,
    )
    deadline = time.monotonic() + daemon_boot_timeout
    while time.monotonic() < deadline and not os.path.exists(sock_path):
        time.sleep(0.2)
        if daemon.poll() is not None:
            pytest.fail(f"daemon died:\n{daemon.stdout.read()}")
    if not os.path.exists(sock_path):
        daemon.kill()
        pytest.fail("daemon never created the edge socket")

    args = [str(edge_bin), "--listen", str(edge_http),
            "--backend", sock_path]
    if edge_grpc:
        args += ["--grpc-listen", str(edge_grpc)]
    edge = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    probe_port = edge_grpc or edge_http
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if edge.poll() is not None:
            daemon.kill()
            pytest.fail(f"edge died:\n{edge.stdout.read()}")
        try:
            socket.create_connection(
                ("127.0.0.1", probe_port), timeout=1
            ).close()
            return daemon, edge
        except OSError:
            time.sleep(0.05)
    edge.kill()
    daemon.kill()
    pytest.fail("edge never started listening")
