"""logging_setup parity tests (reference logging/logging.go:25-54)."""

import pytest


def test_logging_setup_levels_and_json():
    """logging_setup parity with the reference's logrus surface
    (logging/logging.go:25-54): every logrus spelling parses, unknown
    names raise, and the JSON formatter emits one object per line with
    the category field."""
    import json as _json
    import logging as _logging

    from gubernator_tpu.serve.logging_setup import JsonFormatter, parse_level

    for name, want in [
        ("panic", _logging.CRITICAL), ("fatal", _logging.CRITICAL),
        ("error", _logging.ERROR), ("warning", _logging.WARNING),
        ("warn", _logging.WARNING), ("info", _logging.INFO),
        ("debug", _logging.DEBUG), ("trace", _logging.DEBUG),
        (" INFO ", _logging.INFO),  # trimmed + case-insensitive
    ]:
        assert parse_level(name) == want, name
    with pytest.raises(ValueError, match="unknown log level"):
        parse_level("loud")

    rec = _logging.LogRecord(
        name="gubernator_tpu.instance", level=_logging.WARNING,
        pathname=__file__, lineno=1, msg="peer %s down", args=("x:1",),
        exc_info=None,
    )
    out = _json.loads(JsonFormatter().format(rec))
    assert out["level"] == "warning"
    assert out["category"] == "gubernator_tpu.instance"
    assert out["msg"] == "peer x:1 down"
