"""In-tree fake etcd v3 server over REAL gRPC.

Implements exactly the KV/Lease/Watch slice the vendored client
(serve/etcd_client.py) speaks, using the same vendored etcd protos — so
the client's live wire path (streams included) executes against a real
grpc server in-process. Parity with real etcd rests on the protos'
field-number fidelity (api/proto/etcd_rpc.proto); the same test suite
runs against a live etcd when GUBER_TEST_ETCD names an endpoint.

Deliberately tiny: single-tenant, no auth, no transactions, no
compaction; lease expiry is driven by revoke_lease() rather than a
clock, so tests control the failure injection.
"""

from __future__ import annotations

import queue
import threading
from concurrent import futures
from typing import Dict, List, Tuple

import grpc

from gubernator_tpu.api.proto.gen import etcd_mvcc_pb2 as mvcc
from gubernator_tpu.api.proto.gen import etcd_rpc_pb2 as rpc


class FakeEtcd:
    def __init__(self):
        self._lock = threading.Lock()
        self._rev = 1
        self._kv: Dict[bytes, Tuple[bytes, int, int, int, int]] = {}
        # key -> (value, lease, create_rev, mod_rev, version)
        self._leases: Dict[int, int] = {}  # id -> ttl (alive)
        self._next_lease = 1000
        self._watches: List[Tuple[bytes, bytes, "queue.Queue"]] = []
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._register_services()
        self.port = self.server.add_insecure_port("127.0.0.1:0")

    def start(self):
        self.server.start()
        return self

    def stop(self):
        self.server.stop(grace=0.2)

    # -- test hooks ---------------------------------------------------------

    def revoke_lease(self, lease_id: int) -> None:
        """Simulate lease expiry: drop the lease and its keys (with
        DELETE watch events), like real etcd at TTL expiry."""
        with self._lock:
            self._leases.pop(lease_id, None)
            dead = [
                k for k, (_v, ls, *_rest) in self._kv.items()
                if ls == lease_id
            ]
            for k in dead:
                self._delete_locked(k)

    def lease_ids(self):
        with self._lock:
            return set(self._leases)

    def keys(self):
        with self._lock:
            return sorted(self._kv)

    # -- internals ----------------------------------------------------------

    def _kv_proto(self, key: bytes) -> mvcc.KeyValue:
        v, ls, cr, mr, ver = self._kv[key]
        return mvcc.KeyValue(
            key=key, value=v, lease=ls, create_revision=cr,
            mod_revision=mr, version=ver,
        )

    def _notify_locked(self, ev: mvcc.Event) -> None:
        for start, end, q in self._watches:
            if start <= ev.kv.key < end:
                q.put(ev)

    def _delete_locked(self, key: bytes) -> None:
        kv = self._kv_proto(key)
        del self._kv[key]
        self._rev += 1
        self._notify_locked(
            mvcc.Event(type=mvcc.Event.DELETE, kv=mvcc.KeyValue(key=kv.key))
        )

    # -- RPC handlers -------------------------------------------------------

    def _Put(self, req: rpc.PutRequest, ctx) -> rpc.PutResponse:
        with self._lock:
            if req.lease and req.lease not in self._leases:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "lease not found")
            old = self._kv.get(req.key)
            self._rev += 1
            if old is None:
                self._kv[req.key] = (req.value, req.lease, self._rev,
                                     self._rev, 1)
            else:
                self._kv[req.key] = (req.value, req.lease, old[2],
                                     self._rev, old[4] + 1)
            self._notify_locked(
                mvcc.Event(type=mvcc.Event.PUT, kv=self._kv_proto(req.key))
            )
            return rpc.PutResponse(
                header=rpc.ResponseHeader(revision=self._rev)
            )

    def _Range(self, req: rpc.RangeRequest, ctx) -> rpc.RangeResponse:
        with self._lock:
            if req.range_end:
                keys = [
                    k for k in sorted(self._kv)
                    if req.key <= k < req.range_end
                ]
            else:
                keys = [req.key] if req.key in self._kv else []
            kvs = [self._kv_proto(k) for k in keys]
            return rpc.RangeResponse(
                header=rpc.ResponseHeader(revision=self._rev),
                kvs=kvs, count=len(kvs),
            )

    def _DeleteRange(self, req, ctx) -> rpc.DeleteRangeResponse:
        with self._lock:
            if req.range_end:
                keys = [
                    k for k in sorted(self._kv)
                    if req.key <= k < req.range_end
                ]
            else:
                keys = [req.key] if req.key in self._kv else []
            for k in keys:
                self._delete_locked(k)
            return rpc.DeleteRangeResponse(
                header=rpc.ResponseHeader(revision=self._rev),
                deleted=len(keys),
            )

    def _LeaseGrant(self, req, ctx) -> rpc.LeaseGrantResponse:
        with self._lock:
            self._next_lease += 1
            self._leases[self._next_lease] = req.TTL
            return rpc.LeaseGrantResponse(ID=self._next_lease, TTL=req.TTL)

    def _LeaseRevoke(self, req, ctx) -> rpc.LeaseRevokeResponse:
        self.revoke_lease(req.ID)
        return rpc.LeaseRevokeResponse()

    def _LeaseKeepAlive(self, request_iterator, ctx):
        for req in request_iterator:
            with self._lock:
                ttl = self._leases.get(req.ID, 0)
            # real etcd answers TTL=0 for an expired/unknown lease —
            # the signal the pool's refresh turns into a re-register
            yield rpc.LeaseKeepAliveResponse(ID=req.ID, TTL=ttl)

    def _Watch(self, request_iterator, ctx):
        q: "queue.Queue" = queue.Queue()
        created = threading.Event()
        stop = threading.Event()

        def reader():
            try:
                for req in request_iterator:
                    which = req.WhichOneof("request_union")
                    if which == "create_request":
                        cr = req.create_request
                        with self._lock:
                            self._watches.append(
                                (cr.key, cr.range_end or cr.key + b"\0", q)
                            )
                        created.set()
                    elif which == "cancel_request":
                        stop.set()
                        q.put(None)
            except Exception:
                pass
            finally:
                stop.set()
                q.put(None)

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        created.wait(timeout=5)
        yield rpc.WatchResponse(created=True, watch_id=1)
        try:
            while not stop.is_set():
                ev = q.get()
                if ev is None:
                    break
                yield rpc.WatchResponse(watch_id=1, events=[ev])
        finally:
            with self._lock:
                self._watches = [
                    w for w in self._watches if w[2] is not q
                ]

    def _register_services(self):
        def unary(fn, req_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        kv = {
            "Put": unary(self._Put, rpc.PutRequest),
            "Range": unary(self._Range, rpc.RangeRequest),
            "DeleteRange": unary(self._DeleteRange, rpc.DeleteRangeRequest),
        }
        lease = {
            "LeaseGrant": unary(self._LeaseGrant, rpc.LeaseGrantRequest),
            "LeaseRevoke": unary(self._LeaseRevoke, rpc.LeaseRevokeRequest),
            "LeaseKeepAlive": grpc.stream_stream_rpc_method_handler(
                self._LeaseKeepAlive,
                request_deserializer=rpc.LeaseKeepAliveRequest.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
        }
        watch = {
            "Watch": grpc.stream_stream_rpc_method_handler(
                self._Watch,
                request_deserializer=rpc.WatchRequest.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
        }
        self.server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler("etcdserverpb.KV", kv),
            grpc.method_handlers_generic_handler("etcdserverpb.Lease", lease),
            grpc.method_handlers_generic_handler("etcdserverpb.Watch", watch),
        ))
