"""Edge pre-hashed fast path (GEB4) e2e against a real device backend.

The edge hashes name+"_"+key with its own from-spec XXH64 and ships
dense records; the daemon's bridge views them as numpy arrays and drives
the batcher's array path — zero per-item Python. These tests pin:

- bit-exact hash parity between edge.cc's xxh64 and the daemon's native
  hasher (shared rate-limit state between edge-served and directly-served
  traffic is only possible if both address the same store slot);
- GLOBAL items still route through the string (GEB1) path with full
  instance semantics;
- per-item validation errors survive (empty-key items force GEB1);
- fast-path traffic still feeds the distinct-key estimator.

Requires the edge binary; the daemon runs the single-chip tpu backend on
CPU (GUBER_JAX_PLATFORM=cpu) like the other daemon e2e tests.
"""

import json
import os
import pathlib
import urllib.request

import grpc
import pytest

from gubernator_tpu.api.grpc_glue import V1Stub
from gubernator_tpu.api.proto.gen import gubernator_pb2

from tests._util import edge_binary

ROOT = pathlib.Path(__file__).resolve().parent.parent
EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

# dynamic per-process ports + pid-scoped socket: this module's old
# fixed 1949x block collided with its own incarnation inside the ASan
# suite's subprocess runs under full-suite load (r8 deflake; see the
# matching note in test_edge_cluster.py)
from tests._util import free_ports as _free_ports  # noqa: E402

DAEMON_GRPC, DAEMON_HTTP, EDGE_HTTP, EDGE_GRPC = _free_ports(4)
SOCK = f"/tmp/guber-edge-fast-pytest-{os.getpid()}.sock"


@pytest.fixture(scope="module")
def stack():
    from tests._util import spawn_daemon_edge

    daemon, edge = spawn_daemon_edge(
        dict(
            GUBER_BACKEND="tpu",
            GUBER_JAX_PLATFORM="cpu",
            GUBER_STORE_SLOTS=str(1 << 10),
            GUBER_GRPC_ADDRESS=f"127.0.0.1:{DAEMON_GRPC}",
            GUBER_HTTP_ADDRESS=f"127.0.0.1:{DAEMON_HTTP}",
            GUBER_EDGE_SOCKET=SOCK,
            JAX_COMPILATION_CACHE_DIR=str(ROOT / ".jax_cache_cpu"),
        ),
        SOCK,
        edge_http=EDGE_HTTP,
        edge_grpc=EDGE_GRPC,
    )
    yield
    edge.kill()
    daemon.terminate()
    daemon.wait(timeout=10)


def _grpc_req(key, hits=1, limit=5, behavior=0):
    return gubernator_pb2.RateLimitReq(
        name="fp", unique_key=key, hits=hits, limit=limit,
        duration=60_000, behavior=behavior,
    )


def _daemon_http(body: dict) -> dict:
    # bounded 503 retry (r15 deflake; see tests/_util.post_json)
    from _util import post_json

    return post_json(
        f"http://127.0.0.1:{DAEMON_HTTP}/v1/GetRateLimits", body
    )


def test_fast_path_shares_state_with_direct_traffic(stack):
    """Two hits through the edge (GEB4, edge-side XXH64) then a peek
    directly at the daemon (native hasher) must see the SAME bucket —
    bit-exact hash parity, or remaining would read 5."""
    v1 = V1Stub(grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}"))
    for expect in (4, 3):
        r = v1.GetRateLimits(
            gubernator_pb2.GetRateLimitsReq(requests=[_grpc_req("parity")])
        )
        assert r.responses[0].remaining == expect

    out = _daemon_http(
        {"requests": [{"name": "fp", "uniqueKey": "parity", "hits": 0,
                       "limit": 5, "duration": 60000}]}
    )
    assert out["responses"][0]["remaining"] == "3"

    # and back through the edge HTTP door (also fast-path eligible)
    from _util import post_json

    out2 = post_json(
        f"http://127.0.0.1:{EDGE_HTTP}/v1/GetRateLimits",
        {"requests": [{"name": "fp", "uniqueKey": "parity", "hits": 1,
                       "limit": 5, "duration": 60000}]},
    )
    assert out2["responses"][0]["remaining"] == "2"


def test_hash_parity_wide(stack):
    """64 random-ish keys through the edge, then read each directly:
    every bucket must show the consumed hit (any hash mismatch shows up
    as an untouched bucket with remaining == limit)."""
    keys = [f"wide-{i}-é{i % 7}" for i in range(64)]  # incl. utf-8
    v1 = V1Stub(grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}"))
    r = v1.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(
            requests=[_grpc_req(k, limit=9) for k in keys]
        )
    )
    assert all(x.remaining == 8 for x in r.responses)
    out = _daemon_http(
        {"requests": [{"name": "fp", "uniqueKey": k, "hits": 0,
                       "limit": 9, "duration": 60000} for k in keys]}
    )
    assert all(x["remaining"] == "8" for x in out["responses"])


def test_global_items_fall_back_to_string_path(stack):
    """behavior=GLOBAL disqualifies a pending from GEB4; the instance's
    GLOBAL handling (owner-side queue_update on a single node) must
    still answer correctly through the edge."""
    v1 = V1Stub(grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}"))
    r = v1.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(
            requests=[_grpc_req("glob", behavior=gubernator_pb2.GLOBAL)]
        )
    )
    assert r.responses[0].status == gubernator_pb2.UNDER_LIMIT
    assert r.responses[0].remaining == 4


def test_validation_errors_force_string_path(stack):
    v1 = V1Stub(grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}"))
    r = v1.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(
            requests=[
                gubernator_pb2.RateLimitReq(  # empty unique_key
                    name="fp", hits=1, limit=5, duration=60_000
                ),
                _grpc_req("valid-neighbor"),
            ]
        )
    )
    assert "unique_key" in r.responses[0].error
    assert r.responses[1].remaining == 4


def test_fast_path_feeds_distinct_key_estimator(stack):
    before = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{DAEMON_HTTP}/v1/debug/stats", timeout=10
        ).read()
    )["distinct_keys_estimate"]
    v1 = V1Stub(grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}"))
    v1.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(
            requests=[_grpc_req(f"hll-{i}") for i in range(200)]
        )
    )
    after = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{DAEMON_HTTP}/v1/debug/stats", timeout=10
        ).read()
    )["distinct_keys_estimate"]
    assert after >= before + 150
