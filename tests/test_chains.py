"""Hierarchical quota chains (r15): expansion identity, no-partial-
debit, most-restrictive-wins, routing, and the serving surface.

The contracts under test (ISSUE 11 / core/kernels.py
decide_presorted_chain):

- depth-1 identity: a chain-coupled pass where every chain is a
  singleton is BYTE-identical to the plain kernel — responses and the
  written store — and a single-level decide_chain request matches the
  plain decide for the same traffic;
- no-partial-debit: a chain refused at ANY level consumes quota at NO
  level, in one device pass, on the flat and the simulated 8-device
  mesh policies;
- most-restrictive-wins: the shallowest refusing level answers the
  whole request (metadata["chain_level"] names it);
- level counters are REAL counters under the request's name namespace:
  a plain request for (name, level_key) shares the level's state;
- cross-algorithm coexistence: chained token/sliding/GCRA requests
  interleave with plain keys of all four algorithms in one batch;
- the serving tier: instance-level validation (depth bound, GLOBAL
  incompatibility, GUBER_CHAINS=0 kill switch) and the batcher's
  dedicated chain lane end-to-end.
"""

import asyncio

import numpy as np
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    ChainLevel,
    PeerInfo,
    RateLimitReq,
    Status,
)
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve.backends import (
    ExactBackend,
    MeshBackend,
    TpuBackend,
)
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import Instance

T0 = 1_700_000_000_000
ADDR = "127.0.0.1:7977"


def _chain_req(key, hits=1, limit=50, chain=(), algo=Algorithm.TOKEN_BUCKET,
               duration=60_000):
    return RateLimitReq(
        name="chain", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo,
        chain=[ChainLevel(*lv) for lv in chain],
    )


def _peek(backend, key, limit=50, duration=60_000, now=None):
    """Plain read of a level counter's remaining budget."""
    return backend.decide(
        [RateLimitReq(name="chain", unique_key=key, hits=0, limit=limit,
                      duration=duration)],
        [False],
        now=now,
    )[0]


# -- kernel-level depth-1 identity ------------------------------------------


def test_kernel_singleton_chain_identity():
    """The chain-coupled path with every chain a singleton is byte-
    identical to the plain path — responses AND the written store —
    over randomized mixed-algorithm batches with duplicate keys and
    clock advances (decide_chain_arrays vs decide_arrays on twin flat
    engines; this also covers the dedicated chain prep,
    pad_request_chained)."""
    from gubernator_tpu.core.engine import TpuEngine

    rng = np.random.default_rng(5)
    cfg = StoreConfig(rows=16, slots=1 << 8)
    plain = TpuEngine(cfg, buckets=(64,))
    chained = TpuEngine(cfg, buckets=(64,))
    pool = (rng.integers(1, 1 << 60, 24)).astype(np.uint64)
    now = T0
    for step in range(30):
        now += int(rng.choice([0, 1, 40, 700]))
        n = int(rng.integers(1, 32))
        kh = pool[rng.integers(0, pool.shape[0], n)]
        hits = rng.choice([0, 1, 2, 9], n).astype(np.int64)
        limit = rng.choice([3, 8, 30], n).astype(np.int64)
        dur = rng.choice([400, 1000, 60_000], n).astype(np.int64)
        algo = rng.integers(0, 4, n).astype(np.int32)
        gnp = np.zeros(n, bool)
        a = plain.decide_arrays(kh, hits, limit, dur, algo, gnp, now)
        b = chained.decide_chain_arrays(
            kh, hits, limit, dur, algo,
            np.arange(n, dtype=np.int64),  # every chain a singleton
            kh,  # route by own key, like a plain row
            now,
        )
        for x, y in zip(a, b):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=f"step {step}"
            )
    np.testing.assert_array_equal(
        np.asarray(plain.store.data), np.asarray(chained.store.data)
    )


# -- backend-level contracts ------------------------------------------------


def _flat_backend():
    return TpuBackend(StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64))


def _mesh_backend():
    import jax

    assert len(jax.devices()) == 8
    return MeshBackend(StoreConfig(rows=16, slots=256), buckets=(64,))


@pytest.mark.parametrize(
    "mk", [_flat_backend, _mesh_backend], ids=["flat", "mesh8"]
)
def test_single_level_chain_matches_plain(mk):
    """A decide_chain request with NO ancestor levels is byte-identical
    to the plain decide for the same stream (the serving-tier face of
    the depth-1 identity)."""
    a, b = mk(), mk()
    rng = np.random.default_rng(3)
    now = T0
    for step in range(25):
        now += int(rng.choice([0, 1, 40, 700]))
        key = f"sl{rng.integers(6)}"
        hits = int(rng.choice([0, 1, 2, 9]))
        algo = Algorithm(int(rng.integers(0, 4)))
        r = _chain_req(key, hits=hits, limit=5, algo=algo, duration=2000)
        ra = a.decide_chain([r], now=now)[0]
        rb = b.decide([r], [False], now=now)[0]
        assert (
            ra.status, ra.limit, ra.remaining, ra.reset_time
        ) == (rb.status, rb.limit, rb.remaining, rb.reset_time), (
            step, r, ra, rb,
        )


@pytest.mark.parametrize(
    "mk",
    [_flat_backend, _mesh_backend, lambda: ExactBackend(10_000)],
    ids=["flat", "mesh8", "exact"],
)
def test_depth3_no_partial_debit(mk):
    """global -> tenant -> leaf: the tenant exhausts first; the refusal
    must consume quota at NEITHER the global nor the leaf level, and
    the shallowest refusing level is named in metadata. Level state is
    read back through chain-head-routed peeks: on the sharded policy a
    chain's levels live on the HEAD's owner shard (the consolidation
    contract, parallel/sharded.py pad_request_chained), so a plain
    probe of a non-head level would address a different shard's
    (empty) counter."""
    be = mk()
    chain = (("global", 100, 0), ("tenant", 2, 0))
    now = T0
    for i in range(2):
        rl = be.decide_chain(
            [_chain_req("leaf", chain=chain)], now=now + i
        )[0]
        assert rl.status == Status.UNDER_LIMIT, (i, rl)
    # tenant (limit 2) is now exhausted: refusal, no debit anywhere
    for i in range(3):
        rl = be.decide_chain(
            [_chain_req("leaf", chain=chain)], now=now + 2 + i
        )[0]
        assert rl.status == Status.OVER_LIMIT
        assert rl.metadata.get("chain_level") == "1"
        assert rl.limit == 2  # the refusing level answers
    # level counters are real counters under the name namespace,
    # shared with any traffic routed by the same chain head: global
    # consumed exactly 2 (the head reads plainly — it routes to its
    # own shard), the leaf exactly 2, the tenant pinned at 0
    assert _peek(be, "global", limit=100, now=now + 9).remaining == 98
    leaf_read = be.decide_chain(
        [_chain_req("leaf", hits=0,
                    chain=(("global", 100, 0),))],
        now=now + 9,
    )[0]
    assert leaf_read.remaining == 48, leaf_read
    tenant_read = be.decide_chain(
        [_chain_req("tenant", hits=0, limit=2,
                    chain=(("global", 100, 0),))],
        now=now + 9,
    )[0]
    assert tenant_read.remaining == 0, tenant_read


def test_depth3_single_device_pass():
    """All levels of a chained batch debit in ONE engine dispatch."""
    be = _flat_backend()
    calls = []
    orig = be.engine.decide_chain_arrays

    def counting(*a, **kw):
        calls.append(len(a[0]))
        return orig(*a, **kw)

    be.engine.decide_chain_arrays = counting
    chain = (("g", 100, 0), ("t", 50, 0), ("r", 25, 0))
    resps = be.decide_chain(
        [_chain_req("leaf1", chain=chain),
         _chain_req("leaf2", chain=chain)],
        now=T0,
    )
    assert len(resps) == 2
    assert calls == [8], "expected one device pass over all 8 rows"


def test_shallowest_refusal_wins():
    """When several levels would refuse, the SHALLOWEST one answers
    (a global refusal dominates a tenant's)."""
    be = _flat_backend()
    chain = (("G", 1, 0), ("T", 1, 0))
    assert be.decide_chain(
        [_chain_req("L", chain=chain)], now=T0
    )[0].status == Status.UNDER_LIMIT
    rl = be.decide_chain([_chain_req("L", chain=chain)], now=T0 + 1)[0]
    assert rl.status == Status.OVER_LIMIT
    assert rl.metadata.get("chain_level") == "0"
    assert rl.limit == 1


@pytest.mark.parametrize(
    "mk", [_flat_backend, lambda: ExactBackend(10_000)],
    ids=["flat", "exact"],
)
def test_shared_ancestor_survives_mixed_leaf_algorithms(mk):
    """Ancestor levels always decide as TOKEN buckets regardless of
    the request's leaf algorithm (review finding): tenants using GCRA
    and token leaves under ONE shared ancestor must accumulate against
    the same parent counter — per-leaf-algorithm ancestors would flip
    the stored flag every batch, mismatch-recreate, and never reach
    the parent limit."""
    be = mk()
    algos = [Algorithm.GCRA, Algorithm.TOKEN_BUCKET,
             Algorithm.SLIDING_WINDOW]
    for i in range(3):
        rl = be.decide_chain(
            [_chain_req(f"ml{i}", chain=(("shared", 3, 0),),
                        algo=algos[i], limit=10, duration=60_000)],
            now=T0 + i,
        )[0]
        assert rl.status == Status.UNDER_LIMIT, (i, rl)
    # the shared ancestor (limit 3) is now exhausted for EVERY tenant
    rl = be.decide_chain(
        [_chain_req("ml9", chain=(("shared", 3, 0),),
                    algo=Algorithm.GCRA, limit=10, duration=60_000)],
        now=T0 + 5,
    )[0]
    assert rl.status == Status.OVER_LIMIT, rl
    assert rl.metadata.get("chain_level") == "0"


def test_chain_algorithms_coexist_with_plain_traffic():
    """Chained GCRA/sliding requests share one batch with plain keys
    of all four algorithms; the chain's levels store the CHAIN's
    algorithm and plain traffic is unaffected."""
    be = _flat_backend()
    now = T0
    chain = (("cg", 20, 0),)
    for algo in (Algorithm.GCRA, Algorithm.SLIDING_WINDOW):
        rl = be.decide_chain(
            [_chain_req(f"cl-{int(algo)}", chain=chain, algo=algo,
                        limit=10, duration=1000)],
            now=now,
        )[0]
        assert rl.status == Status.UNDER_LIMIT, (algo, rl)
    plain = [
        RateLimitReq(name="chain", unique_key=f"p{a}", hits=1, limit=5,
                     duration=1000, algorithm=Algorithm(a))
        for a in range(4)
    ]
    for rl in be.decide(plain, [False] * 4, now=now + 1):
        assert rl.status == Status.UNDER_LIMIT
        assert rl.remaining == 4


# -- serving tier -----------------------------------------------------------


async def _mk_instance(conf_kw=None):
    conf = ServerConfig(
        grpc_address=ADDR, advertise_address=ADDR, **(conf_kw or {})
    )
    inst = Instance(
        conf, TpuBackend(StoreConfig(rows=16, slots=1 << 8), buckets=(16,))
    )
    inst.start()
    await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
    return inst


def test_instance_chain_lane_and_validation():
    async def run():
        inst = await _mk_instance()
        try:
            chain = (("ig", 100, 0), ("it", 2, 0))
            r1, r2 = await inst.get_rate_limits(
                [_chain_req("il", chain=chain),
                 _chain_req("il", chain=chain)]
            )
            assert r1.status == Status.UNDER_LIMIT
            assert r2.status == Status.UNDER_LIMIT
            (r3,) = await inst.get_rate_limits(
                [_chain_req("il", chain=chain)]
            )
            assert r3.status == Status.OVER_LIMIT
            assert r3.metadata.get("chain_level") == "1"

            # depth bound (GUBER_CHAIN_MAX_DEPTH defaults to 3 ancestors)
            deep = _chain_req(
                "il", chain=[("a", 1, 0), ("b", 1, 0), ("c", 1, 0),
                             ("d", 1, 0)]
            )
            (rd,) = await inst.get_rate_limits([deep])
            assert "GUBER_CHAIN_MAX_DEPTH" in rd.error

            # GLOBAL behavior is incompatible with a chain
            g = _chain_req("il", chain=chain)
            g.behavior = Behavior.GLOBAL
            (rg,) = await inst.get_rate_limits([g])
            assert "GLOBAL" in rg.error

            # empty level key refused per item
            (re_,) = await inst.get_rate_limits(
                [_chain_req("il", chain=(("", 1, 0),))]
            )
            assert "unique_key" in re_.error
        finally:
            await inst.stop()

    asyncio.run(run())


def test_peer_door_validates_chains():
    """get_peer_rate_limits enforces chain validation with the
    RECEIVING node's config (review finding): the depth bound and the
    GUBER_CHAINS kill switch hold at the peer door, not only the
    client door — a hostile peer cannot demand unbounded device-row
    expansion."""

    async def run():
        inst = await _mk_instance()
        try:
            deep = _chain_req(
                "pd", chain=[("a", 1, 0), ("b", 1, 0), ("c", 1, 0),
                             ("d", 1, 0)]
            )
            ok = _chain_req("pd", chain=(("pg", 5, 0),))
            rd, rok = await inst.get_peer_rate_limits([deep, ok])
            assert "GUBER_CHAIN_MAX_DEPTH" in rd.error
            assert rok.status == Status.UNDER_LIMIT and not rok.error
        finally:
            await inst.stop()

    async def run_off():
        inst = await _mk_instance({"chains": False})
        try:
            (r,) = await inst.get_peer_rate_limits(
                [_chain_req("pd2", chain=(("pg", 5, 0),))]
            )
            assert "GUBER_CHAINS" in r.error
        finally:
            await inst.stop()

    asyncio.run(run())
    asyncio.run(run_off())


def test_instance_chain_kill_switch():
    async def run():
        inst = await _mk_instance({"chains": False})
        try:
            (r,) = await inst.get_rate_limits(
                [_chain_req("ks", chain=(("g", 5, 0),))]
            )
            assert "GUBER_CHAINS" in r.error
            # plain traffic unaffected
            (p,) = await inst.get_rate_limits([_chain_req("ks2")])
            assert p.status == Status.UNDER_LIMIT
        finally:
            await inst.stop()

    asyncio.run(run())


@pytest.mark.parametrize(
    "mk", [_flat_backend, lambda: ExactBackend(10_000)],
    ids=["flat", "exact"],
)
def test_duplicate_level_key_no_partial_debit(mk):
    """A chain naming the SAME counter twice (ancestor == leaf) judges
    the request against the cumulative charge: 6+6 > 10 refuses the
    whole chain and consumes NOTHING (the review-found ExactBackend
    hole: its peek pass saw the pre-charge budget twice, charged the
    first occurrence, refused the second — a partial debit)."""
    be = mk()
    r = RateLimitReq(
        name="chain", unique_key="dup", hits=6, limit=10,
        duration=60_000, chain=[ChainLevel("dup", 10, 0)],
    )
    rl = be.decide_chain([r], now=T0)[0]
    assert rl.status == Status.OVER_LIMIT, rl
    assert _peek(be, "dup", limit=10, now=T0 + 1).remaining == 10
    # and a fitting duplicate chain charges BOTH occurrences
    r2 = RateLimitReq(
        name="chain", unique_key="dup", hits=3, limit=10,
        duration=60_000, chain=[ChainLevel("dup", 10, 0)],
    )
    rl2 = be.decide_chain([r2], now=T0 + 2)[0]
    assert rl2.status == Status.UNDER_LIMIT, rl2
    assert _peek(be, "dup", limit=10, now=T0 + 3).remaining == 4


def test_chain_peek_pass_is_non_mutating_for_leaky():
    """The exact backend's advisory peek pass must not double-apply
    the leaky peek's persisted leak credit (review finding): one
    chained request on a drained leaky leaf sees the SAME budget a
    single sequential pass would — pre-fix the peek credited the
    elapsed leak and the debit pass credited it again, refilling
    chained leaky leaves at ~2x the configured rate."""
    be = ExactBackend(10_000)
    drain = RateLimitReq(
        name="chain", unique_key="lk", hits=10, limit=10,
        duration=10_000, algorithm=Algorithm.LEAKY_BUCKET,
        chain=[ChainLevel("lg", 100, 0)],
    )
    assert be.decide_chain([drain], now=T0)[0].status == (
        Status.UNDER_LIMIT
    )
    # 1s later the leak has refilled exactly 1 (rate = 10/10s)
    peek = RateLimitReq(
        name="chain", unique_key="lk", hits=0, limit=10,
        duration=10_000, algorithm=Algorithm.LEAKY_BUCKET,
        chain=[ChainLevel("lg", 100, 0)],
    )
    rl = be.decide_chain([peek], now=T0 + 1000)[0]
    assert rl.remaining == 1, rl  # pre-fix: 2
    # and a refused chain leaves no trace (no leak-clock advance)
    over = RateLimitReq(
        name="chain", unique_key="lk", hits=5, limit=10,
        duration=10_000, algorithm=Algorithm.LEAKY_BUCKET,
        chain=[ChainLevel("lg", 100, 0)],
    )
    rl2 = be.decide_chain([over], now=T0 + 1000)[0]
    assert rl2.status == Status.OVER_LIMIT, rl2
    # a SECOND peek at the same instant reads 2: the reference's own
    # repeated-peek quirk (a leaky peek persists its credit without
    # advancing the timestamp, so each peek re-credits the same
    # elapsed leak) — one credit per request, exactly like sequential
    # plain peeks, NOT the intra-request double credit under test
    rl3 = be.decide_chain([peek], now=T0 + 1000)[0]
    assert rl3.remaining == 2, rl3


def test_fallbacks_never_decide_chains_as_plain():
    """Owner-unreachable fallbacks must not silently strip a chain to
    its leaf (the review finding): takeover refuses chained items
    per-item (chain levels are not replicated), and degraded mode
    serves them through the LOCAL chain lane with full most-
    restrictive-wins semantics."""

    class FakePeer:
        host = "10.0.0.9:81"

    async def run():
        inst = await _mk_instance({"degraded_local": True})
        try:
            chained = _chain_req("fb", chain=(("fbt", 1, 0),))
            # takeover: repl present, all-chained items -> per-item
            # refusal, never a leaf-only decide
            inst.repl = object()
            taken = await inst._takeover_fallback(
                [(0, chained)], FakePeer(), RuntimeError("down")
            )
            inst.repl = None
            assert taken is not None
            assert "takeover scope" in taken[0].error

            # degraded: full chain semantics against the local store
            d1 = await inst._degraded_fallback(
                [(0, chained)], FakePeer(), RuntimeError("down")
            )
            assert d1[0].status == Status.UNDER_LIMIT
            assert d1[0].metadata.get("degraded") == "true"
            d2 = await inst._degraded_fallback(
                [(0, chained)], FakePeer(), RuntimeError("down")
            )
            # the tenant level (limit 1) is exhausted: the chain is
            # refused at level 0 — a leaf-only decide (limit 50)
            # would have admitted it
            assert d2[0].status == Status.OVER_LIMIT
            assert d2[0].metadata.get("chain_level") == "0"
        finally:
            await inst.stop()

    asyncio.run(run())


def test_level_duration_inheritance():
    """A level duration of 0 inherits the request's; an explicit level
    duration stands on its own."""
    be = _flat_backend()
    rl = be.decide_chain(
        [_chain_req("dl", duration=1000,
                    chain=(("dg", 5, 0), ("dt", 5, 30_000)))],
        now=T0,
    )[0]
    assert rl.status == Status.UNDER_LIMIT
    # the inheriting level's window ends with the request's duration
    assert _peek(be, "dg", limit=5, duration=1000,
                 now=T0 + 1).remaining == 4
    # ...and is gone after it (token window expired -> fresh peek)
    assert _peek(be, "dg", limit=5, duration=1000,
                 now=T0 + 1500).remaining == 5
    # the explicit 30s level still holds its consumed hit
    assert _peek(be, "dt", limit=5, duration=30_000,
                 now=T0 + 1500).remaining == 4


def test_chain_lane_records_frame_stages():
    """r16 frame-coverage audit pin: a GEBC chain frame's batch must
    record the SAME per-frame (batch_queue, device, encode) and
    per-batch (submit_host) stages the decide lanes record — before
    the fix, chained frames added e2e with no stages and silently
    diluted the r7 coverage contract under chained traffic."""
    from gubernator_tpu.client_geb import build_frame
    from gubernator_tpu.serve.edge_bridge import FrameService
    from gubernator_tpu.serve.stages import STAGES

    async def run():
        inst = await _mk_instance()
        try:
            svc = FrameService(inst)
            STAGES.reset()
            frame, is_fast = build_frame(
                [_chain_req("sc", chain=(("sg", 100, 0),))],
                fast=False, windowed=True, frame_id=7,
            )
            assert not is_fast
            resp = await svc.serve_frame_bytes(frame)
            assert resp  # well-formed GEB4 answer
            snap = STAGES.snapshot()
            st = snap["stages"]
            assert snap["frames"] == 1
            for stage in ("batch_queue", "device", "encode",
                          "submit_host"):
                assert st.get(stage, {}).get("count", 0) >= 1, (
                    stage, st,
                )
            # the recorded per-frame stages actually tile frame e2e
            # (loose floor: sub-ms spans on a loaded CI box)
            assert snap["coverage"] > 0.1, snap
        finally:
            await inst.stop()

    asyncio.run(run())


def test_chain_frame_flag_prefers_plain_lane_on_mixed_frames():
    """One frame = one per-frame span (the r7 chunk convention): a
    frame carrying BOTH plain and chained items flags only the plain
    lane; a chain-only frame flags the chain lane."""
    from gubernator_tpu.serve.stages import STAGES

    async def run():
        inst = await _mk_instance()
        try:
            STAGES.reset()
            await inst.get_rate_limits(
                [
                    _chain_req("mx", chain=(("mg", 100, 0),)),
                    _chain_req("plain-mx"),  # no chain levels
                ],
                stage_frame=True,
            )
            snap = STAGES.snapshot()["stages"]
            # exactly ONE frame-attributed device span between the two
            # lanes (the plain lane's), not two
            assert snap.get("device", {}).get("count") == 1, snap
            assert snap.get("batch_queue", {}).get("count") == 1, snap
        finally:
            await inst.stop()

    asyncio.run(run())
