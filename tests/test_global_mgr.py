"""GlobalManager unit tests with a fake instance — direct coverage of
the gossip loops that e2e tests only exercise as a black box (reference
global.go:29-232 behaviors):

- per-key hit aggregation before a flush (one forwarded request carries
  the summed hits),
- immediate flush at global_batch_limit vs coalescing window below it,
- broadcast dedup (last queued state wins per key), owner-peer skip,
- one failing peer must not block the others or kill the loop.
"""

import asyncio
from dataclasses import dataclass, field

import pytest

from gubernator_tpu.api.types import Behavior, RateLimitReq, RateLimitResp
from gubernator_tpu.serve.config import BehaviorConfig
from gubernator_tpu.serve.global_mgr import GlobalManager


def _req(key: str, hits=1, behavior=Behavior.GLOBAL) -> RateLimitReq:
    return RateLimitReq(
        name="gm", unique_key=key, hits=hits, limit=10, duration=60_000,
        behavior=behavior,
    )


@dataclass
class FakePeer:
    host: str
    is_owner: bool = False
    mesh_local: bool = False
    fail: bool = False
    hit_batches: list = field(default_factory=list)
    update_batches: list = field(default_factory=list)

    async def get_peer_rate_limits(self, reqs):
        if self.fail:
            raise RuntimeError(f"{self.host} unreachable")
        self.hit_batches.append(list(reqs))
        return [RateLimitResp(limit=r.limit) for r in reqs]

    async def update_peer_globals(self, updates):
        if self.fail:
            raise RuntimeError(f"{self.host} unreachable")
        self.update_batches.append(list(updates))


class FakeInstance:
    """Key ownership by prefix: key 'a…' -> peer A, 'b…' -> peer B…"""

    def __init__(self, peers):
        self.peers = peers
        self.decided = []
        self.installed = []  # local replica installs (r21 mesh path)

    async def update_peer_globals(self, updates):
        self.installed.append(list(updates))

    def get_peer(self, hash_key):
        # hash_key is "name_uniquekey"; route on the unique key's first char
        first = hash_key.split("_", 1)[1][0]
        return self.peers[first]

    def peer_list(self):
        return list(self.peers.values())

    async def decide_local(self, reqs, gnp):
        self.decided.append(list(reqs))
        return [
            RateLimitResp(limit=r.limit, remaining=r.limit - 3)
            for r in reqs
        ]


def _conf(**kw):
    base = dict(
        global_sync_wait=0.02, global_batch_limit=1000, global_timeout=2.0
    )
    base.update(kw)
    return BehaviorConfig(**base)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=20))


def test_hits_aggregate_per_key_before_flush():
    peers = {"a": FakePeer("A"), "b": FakePeer("B", is_owner=True)}
    inst = FakeInstance(peers)

    async def main():
        gm = GlobalManager(_conf(), inst)
        gm.start()
        # three hits on one key + one on another, all owned by peer A —
        # queued within one window so they coalesce into ONE flush
        gm.queue_hit(_req("a1", hits=2))
        gm.queue_hit(_req("a1", hits=3))
        gm.queue_hit(_req("a2", hits=1))
        for _ in range(200):
            if peers["a"].hit_batches:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    assert len(peers["a"].hit_batches) == 1
    sent = {r.unique_key: r.hits for r in peers["a"].hit_batches[0]}
    assert sent == {"a1": 5, "a2": 1}  # summed per key (global.go:78-86)
    assert peers["b"].hit_batches == []  # nothing owned by B was queued


def test_batch_limit_flushes_without_window():
    peers = {"a": FakePeer("A"), "b": FakePeer("B", is_owner=True)}
    inst = FakeInstance(peers)

    async def main():
        # window absurdly long: only the batch-limit path can flush
        gm = GlobalManager(
            _conf(global_sync_wait=30.0, global_batch_limit=3), inst
        )
        gm.start()
        for i in range(3):
            gm.queue_hit(_req(f"a{i}"))
        for _ in range(200):
            if peers["a"].hit_batches:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    assert len(peers["a"].hit_batches) == 1
    assert len(peers["a"].hit_batches[0]) == 3


def test_broadcast_dedup_last_wins_and_skips_owner():
    peers = {
        "a": FakePeer("A"),
        "b": FakePeer("B", is_owner=True),
        "c": FakePeer("C"),
    }
    inst = FakeInstance(peers)

    async def main():
        gm = GlobalManager(_conf(), inst)
        gm.start()
        gm.queue_update(_req("a1", hits=1))
        gm.queue_update(_req("a1", hits=9))  # same key dedups
        gm.queue_update(_req("c7", hits=1))
        for _ in range(200):
            if peers["a"].update_batches and peers["c"].update_batches:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    # the owner peer (self) is never broadcast to (global.go:215-229)
    assert peers["b"].update_batches == []
    # both non-owners got ONE batch of the two deduped keys
    for p in ("a", "c"):
        assert len(peers[p].update_batches) == 1
        keys = sorted(k for k, _ in peers[p].update_batches[0])
        assert keys == ["gm_a1", "gm_c7"]
    # the peek decide was a zero-hit non-GLOBAL read (global.go:200-203)
    (peek_batch,) = inst.decided
    assert all(r.hits == 0 for r in peek_batch)
    assert all(r.behavior == Behavior.BATCHING for r in peek_batch)


def test_failing_peer_does_not_block_others_or_kill_loops():
    peers = {
        "a": FakePeer("A", fail=True),
        "b": FakePeer("B", is_owner=True),
        "c": FakePeer("C"),
    }
    inst = FakeInstance(peers)

    async def main():
        gm = GlobalManager(_conf(), inst)
        gm.start()
        gm.queue_hit(_req("a1"))  # flush to A raises
        gm.queue_hit(_req("c1"))  # must still reach C
        for _ in range(200):
            if peers["c"].hit_batches:
                break
            await asyncio.sleep(0.01)
        # broadcast path: A fails, C still receives
        gm.queue_update(_req("c2"))
        for _ in range(200):
            if peers["c"].update_batches:
                break
            await asyncio.sleep(0.01)
        # loops survived both errors: another hit still flushes
        gm.queue_hit(_req("c3"))
        for _ in range(200):
            if len(peers["c"].hit_batches) >= 2:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    assert len(peers["c"].hit_batches) >= 2
    assert peers["c"].update_batches, "broadcast blocked by failing peer"


# -- r20 mesh-native flush: per-destination path selection ------------------


def _flush_bytes(path: str) -> float:
    from gubernator_tpu.serve import metrics

    return metrics.GLOBAL_FLUSH_BYTES.labels(path=path)._value.get()


def test_self_owned_hits_short_circuit_local_apply():
    """r20 satellite pin: hits whose ring owner is THIS node must go
    through the local apply path (one in-mesh collective / local
    decide), never a loopback gossip RPC to our own door — and the
    flush trace span must carry the hop-count split proving it."""
    from gubernator_tpu.serve.tracing import Tracer

    peers = {"a": FakePeer("A"), "b": FakePeer("B", is_owner=True)}
    inst = FakeInstance(peers)
    inst.tracer = Tracer(sample=1.0)
    before_mesh = _flush_bytes("mesh")
    before_rpc = _flush_bytes("rpc")

    async def main():
        gm = GlobalManager(_conf(), inst)
        gm.start()
        gm.queue_hit(_req("b1", hits=2))
        gm.queue_hit(_req("b1", hits=3))  # aggregates with the first
        gm.queue_hit(_req("b2", hits=1))
        gm.queue_hit(_req("a1", hits=4))  # off-mesh peer: stays RPC
        for _ in range(200):
            if peers["a"].hit_batches and inst.decided:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    # self-destined keys NEVER loop back through our own gossip door
    assert peers["b"].hit_batches == []
    # they landed on the local apply path (decide_local fallback on the
    # fake), aggregated per key exactly like the RPC chunks
    (local_batch,) = inst.decided
    assert {r.unique_key: r.hits for r in local_batch} == {"b1": 5, "b2": 1}
    # the off-mesh peer still got its gossip send
    assert len(peers["a"].hit_batches) == 1
    assert {r.unique_key for r in peers["a"].hit_batches[0]} == {"a1"}
    # byte split is observable per path
    assert _flush_bytes("mesh") > before_mesh
    assert _flush_bytes("rpc") > before_rpc
    # trace-span evidence: one mesh hop (one collective) regardless of
    # how many self-owned keys flushed, one RPC hop for the one peer
    spans = [
        sp
        for tr in inst.tracer.recorder.snapshot()["traces"]
        if tr["door"] == "global_flush"
        for sp in tr["spans"]
        if sp["name"] == "global_flush_hits"
    ]
    assert spans, "flush produced no global_flush_hits span"
    ann = spans[0]["annotations"]
    assert ann["hops_mesh"] == 1
    assert ann["hops_rpc"] == 1
    assert ann["keys_mesh"] == 2
    assert ann["keys_rpc"] == 1


def test_global_mesh_off_restores_rpc_fanout():
    """GUBER_GLOBAL_MESH=0 escape hatch: self-destined hits go back
    through the gossip door like any other peer (pre-r20 behavior, and
    the perf gate's A side)."""
    peers = {"a": FakePeer("A"), "b": FakePeer("B", is_owner=True)}
    inst = FakeInstance(peers)

    async def main():
        gm = GlobalManager(_conf(global_mesh=False), inst)
        gm.start()
        gm.queue_hit(_req("b1", hits=2))
        for _ in range(200):
            if peers["b"].hit_batches:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    assert len(peers["b"].hit_batches) == 1
    assert inst.decided == []


def test_mesh_local_broadcast_short_circuits_install():
    """r21 satellite pin: the r20 per-destination split applied to the
    BROADCAST loop. Peers whose replicas ride this node's mesh
    (PeerInfo.mesh_local) must be covered by ONE local install of the
    whole batch — never a per-peer UpdatePeerGlobals RPC — while
    off-mesh peers keep the RPC fan-out; the flush trace span carries
    the hop split proving the collapse."""
    from gubernator_tpu.serve.tracing import Tracer

    peers = {
        "a": FakePeer("A"),
        "b": FakePeer("B", is_owner=True),
        "c": FakePeer("C", mesh_local=True),
        "d": FakePeer("D", mesh_local=True),
    }
    inst = FakeInstance(peers)
    inst.tracer = Tracer(sample=1.0)
    before_mesh = _flush_bytes("mesh")
    before_rpc = _flush_bytes("rpc")

    async def main():
        gm = GlobalManager(_conf(), inst)
        gm.start()
        gm.queue_update(_req("a1"))
        gm.queue_update(_req("c7"))
        for _ in range(200):
            if peers["a"].update_batches and inst.installed:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    # mesh-local peers NEVER got an RPC; the owner stays skipped
    assert peers["c"].update_batches == []
    assert peers["d"].update_batches == []
    assert peers["b"].update_batches == []
    # ONE local install of the whole deduped batch covers c AND d
    (installed,) = inst.installed
    assert sorted(k for k, _ in installed) == ["gm_a1", "gm_c7"]
    # the off-mesh peer still got its full broadcast over RPC
    assert len(peers["a"].update_batches) == 1
    assert sorted(k for k, _ in peers["a"].update_batches[0]) == [
        "gm_a1", "gm_c7",
    ]
    # byte split is observable per path
    assert _flush_bytes("mesh") > before_mesh
    assert _flush_bytes("rpc") > before_rpc
    # trace-span evidence: one mesh hop covers BOTH mesh-local peers;
    # the RPC path pays one hop for the one off-mesh peer
    spans = [
        sp
        for tr in inst.tracer.recorder.snapshot()["traces"]
        if tr["door"] == "global_broadcast"
        for sp in tr["spans"]
        if sp["name"] == "global_flush_updates"
    ]
    assert spans, "broadcast produced no global_flush_updates span"
    ann = spans[0]["annotations"]
    assert ann["hops_mesh"] == 1
    assert ann["hops_rpc"] == 1
    assert ann["keys_mesh"] == 2
    assert ann["keys_rpc"] == 2
    assert ann["peers_mesh"] == 2
    assert ann["peers_rpc"] == 1


def test_mesh_local_broadcast_off_restores_rpc_fanout():
    """GUBER_GLOBAL_MESH=0 escape hatch on the broadcast loop: a
    mesh_local peer is fanned out to over RPC like any other peer
    (pre-r21 behavior)."""
    peers = {
        "b": FakePeer("B", is_owner=True),
        "c": FakePeer("C", mesh_local=True),
    }
    inst = FakeInstance(peers)

    async def main():
        gm = GlobalManager(_conf(global_mesh=False), inst)
        gm.start()
        gm.queue_update(_req("c1"))
        for _ in range(200):
            if peers["c"].update_batches:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    assert len(peers["c"].update_batches) == 1
    assert inst.installed == []


def test_mesh_local_install_prefers_instance_hook():
    """When the instance exposes update_peer_globals_local, the
    mesh-local broadcast chunk must use it over update_peer_globals —
    that is where an embedder hangs a one-collective install."""
    peers = {
        "b": FakePeer("B", is_owner=True),
        "c": FakePeer("C", mesh_local=True),
    }
    inst = FakeInstance(peers)
    hooked = []

    async def hook(updates):
        hooked.append(list(updates))

    inst.update_peer_globals_local = hook

    async def main():
        gm = GlobalManager(_conf(), inst)
        gm.start()
        gm.queue_update(_req("c1"))
        for _ in range(200):
            if hooked:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    assert inst.installed == []
    (batch,) = hooked
    assert [k for k, _ in batch] == ["gm_c1"]


def test_local_apply_prefers_instance_hook():
    """When the instance exposes apply_global_hits_local (the real
    server does), the flush must call it instead of decide_local — that
    hook is where the one-collective apply lives."""
    peers = {"b": FakePeer("B", is_owner=True)}
    inst = FakeInstance(peers)
    applied = []

    async def hook(reqs):
        applied.append(list(reqs))

    inst.apply_global_hits_local = hook

    async def main():
        gm = GlobalManager(_conf(), inst)
        gm.start()
        gm.queue_hit(_req("b1", hits=7))
        for _ in range(200):
            if applied:
                break
            await asyncio.sleep(0.01)
        await gm.stop()

    run(main())
    assert inst.decided == []
    (batch,) = applied
    assert [(r.unique_key, r.hits) for r in batch] == [("b1", 7)]
