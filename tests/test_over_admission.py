"""Over-admission under eviction, characterized against the exact oracle
(BASELINE config 4's "bounded over-count"; VERDICT r1 weak #7).

The slot store's eviction contract: when a bucket's ways fill, the
entry with the earliest expiry is evicted, and a still-live evicted
window loses its consumed count — the key gets a fresh window on next
sight, briefly over-admitting (same contract as reference LRU eviction
/ restart state loss, architecture.md:5-11). This test MEASURES that
over-admission rate for zipf traffic at several store load factors vs
an unbounded exact oracle, and pins the bound the README advertises.
"""

import numpy as np
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    Status,
)
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.engine import TpuEngine
from gubernator_tpu.core.oracle import get_rate_limit
from gubernator_tpu.core.store import StoreConfig

T0 = 1_700_000_000_000


def _over_admission_rate(n_keys: int, capacity_cfg: StoreConfig,
                         steps: int = 30, batch: int = 512) -> float:
    """Fraction of requests the engine admits that the exact oracle
    (no eviction, infinite memory) would refuse."""
    engine = TpuEngine(capacity_cfg, buckets=(batch,))
    cache = LRUCache(1 << 30)  # effectively unbounded: the exact twin
    rng = np.random.default_rng(7)

    keys = [f"oa:{i}" for i in range(n_keys)]
    # Deterministic synthetic slot hashes instead of slot_hash_batch: the
    # native build hashes with XXH64, the fallback with blake2b, and the
    # pinned rates below must not depend on which one is loaded (bucket
    # collision patterns differ per hash function). The engine only needs
    # keys[i] <-> hashes_all[i] to be a stable injection.
    hashes_all = np.random.default_rng(11).integers(
        0, 1 << 63, size=n_keys, dtype=np.uint64
    ) << np.uint64(1) | np.uint64(1)

    over_admit = 0
    total = 0
    now = T0
    for step in range(steps):
        now += 50
        zipf = rng.zipf(1.3, size=batch) % n_keys
        kh = hashes_all[zipf]
        status, _, _, _ = engine.decide_arrays(
            kh,
            np.ones(batch, np.int64),
            np.full(batch, 10, np.int64),
            np.full(batch, 10_000_000, np.int64),
            np.zeros(batch, np.int32),
            np.zeros(batch, bool),
            now,
        )
        for i in range(batch):
            r = RateLimitReq(
                name="oa", unique_key=keys[zipf[i]], hits=1, limit=10,
                duration=10_000_000, algorithm=Algorithm.TOKEN_BUCKET,
            )
            want = get_rate_limit(cache, r, now=now)
            total += 1
            if (
                status[i] == int(Status.UNDER_LIMIT)
                and want.status == Status.OVER_LIMIT
            ):
                over_admit += 1
    return over_admit / total


@pytest.mark.parametrize(
    "n_keys,max_rate",
    [
        (400, 0.0),  # 39% load: exact behavior, zero over-admission
        (700, 0.0),  # 68% load: still exact
        (900, 0.01),  # 88% load: rare way-exhaustion evictions (~0.26%)
        (1300, 0.02),  # 127% load (over capacity): ~0.63%
        (2000, 0.04),  # 195% load: ~1.5%, still bounded
    ],
)
def test_over_admission_bounded(n_keys, max_rate):
    """Store: 16 ways x 64 buckets = 1024 entries (the production way
    geometry). Asserted bounds give the measured rates 2-3x headroom;
    the README performance table quotes the measured numbers.

    These rates depend on the ranked-empty-way writeback: before it,
    simultaneous fresh keys colliding in a bucket dropped all but one
    creation, measuring ~3% over-admission even at 39% load."""
    cfg = StoreConfig(rows=16, slots=64)
    rate = _over_admission_rate(n_keys, cfg)
    assert rate <= max_rate, (
        f"over-admission {rate:.4f} exceeds {max_rate} at {n_keys} keys"
    )


def _bucket_of(kh: np.ndarray, slots: int) -> np.ndarray:
    """The store's OWN bucket derivation (group_sort_key's high bits), so
    the crafted collisions track any future change to the store's
    hashing instead of silently spreading across buckets."""
    from gubernator_tpu.core.store import group_sort_key_np

    return (group_sort_key_np(kh, slots) >> np.uint64(32)).astype(np.int64)


def _colliding_hashes(slots: int, bucket: int, count: int) -> np.ndarray:
    """Distinct synthetic key hashes that all land in `bucket` of a
    `slots`-bucket store (and carry distinct fingerprints)."""
    rng = np.random.default_rng(0xC0111DE)
    out = []
    fps = set()
    while len(out) < count:
        kh = rng.integers(1, 1 << 63, size=4096, dtype=np.uint64)
        for h in kh[_bucket_of(kh, slots) == bucket]:
            fp = int(h) >> 32
            if fp and fp not in fps:  # distinct store tags
                fps.add(fp)
                out.append(int(h))
                if len(out) == count:
                    break
    arr = np.asarray(out, np.uint64)
    # the attack is vacuous unless the keys REALLY collide per the
    # store's own derivation
    assert (_bucket_of(arr, slots) == bucket).all()
    return arr


def _decide(engine, kh, now, limit=3):
    n = kh.shape[0]
    status, _, _, _ = engine.decide_arrays(
        kh,
        np.ones(n, np.int64),
        np.full(n, limit, np.int64),
        np.full(n, 10_000_000, np.int64),
        np.zeros(n, np.int32),
        np.zeros(n, bool),
        now,
    )
    return status


def test_adversarial_bucket_collision_within_ways_is_exact():
    """16 distinct keys crafted into ONE 16-way bucket exactly fill it:
    no eviction, zero over-admission — the set-associative geometry
    absorbs the collision attack up to its way count."""
    slots = 64
    engine = TpuEngine(StoreConfig(rows=16, slots=slots), buckets=(64,))
    kh = _colliding_hashes(slots, bucket=5, count=16)
    now = T0
    over = 0
    for step in range(8):  # limit=3: steps 0-2 admit, 3+ must refuse
        now += 50
        status = _decide(engine, kh, now)
        want_over = step >= 3
        if want_over:
            over += int((status == int(Status.UNDER_LIMIT)).sum())
    assert over == 0, f"{over} over-admissions with <=16 colliding keys"


def test_adversarial_bucket_collision_beyond_ways_bounded():
    """32 distinct keys into one 16-way bucket, every batch: the worst
    adversarial shape for the store — each batch evicts up to 16 live
    windows, so evicted keys get fresh windows on revisit. This pins the
    MEASURED worst-case rate (and documents it): over-admission stays
    confined to the attacked bucket and is bounded by its eviction
    churn, not amplified store-wide."""
    slots = 64
    engine = TpuEngine(StoreConfig(rows=16, slots=slots), buckets=(64,))
    cache = LRUCache(1 << 30)
    kh = _colliding_hashes(slots, bucket=5, count=32)
    keys = [f"adv:{i}" for i in range(32)]
    now = T0
    over = total = 0
    for step in range(20):
        now += 50
        status = _decide(engine, kh, now)
        for i in range(32):
            r = RateLimitReq(
                name="adv", unique_key=keys[i], hits=1, limit=3,
                duration=10_000_000, algorithm=Algorithm.TOKEN_BUCKET,
            )
            want = get_rate_limit(cache, r, now=now)
            total += 1
            if (
                status[i] == int(Status.UNDER_LIMIT)
                and want.status == Status.OVER_LIMIT
            ):
                over += 1
    rate = over / total
    # 2x overcommit on one bucket loses up to half the windows per
    # round; the measured steady rate is ~0.4-0.55 of the attacked
    # keys' requests (0.425 on the pinned seed). This is the documented worst case for a targeted
    # collision attack — the reference's LRU at equal capacity likewise
    # sheds state under adversarial churn (architecture.md:5-11); the
    # blast radius here is ONE bucket, not the whole cache.
    assert rate <= 0.65, f"collision-attack over-admission {rate:.3f}"
    # and a control key in another bucket stays exact throughout
    control = _colliding_hashes(slots, bucket=9, count=1)
    ctrl_cache = LRUCache(1 << 30)
    for step in range(6):
        now += 50
        status = _decide(engine, control, now)
        r = RateLimitReq(
            name="adv", unique_key="control", hits=1, limit=3,
            duration=10_000_000, algorithm=Algorithm.TOKEN_BUCKET,
        )
        want = get_rate_limit(ctrl_cache, r, now=now)
        assert int(status[0]) == int(want.status), (step, status, want)


def test_adversarial_cold_storm_revisit():
    """All-distinct cold storm: drive victims to OVER_LIMIT, flood the
    whole store with fresh distinct keys (4x capacity), then revisit the
    victims. Evicted victims get fresh windows — up to 100% of them
    re-admit, the same state-loss contract as the reference's LRU
    evicting at capacity (architecture.md:5-11). The pinned facts: the
    storm itself admits every fresh key exactly once (no phantom
    refusals), and revisit over-admission is bounded by the eviction
    count, not amplified beyond it."""
    slots = 64
    cap = 16 * slots
    engine = TpuEngine(StoreConfig(rows=16, slots=slots), buckets=(1024,))
    rng = np.random.default_rng(0x57012)
    victims = (
        rng.integers(1, 1 << 63, size=64, dtype=np.uint64)
        | np.uint64(1)
    )
    now = T0
    # exhaust the victims (limit=3): 3 admits then OVER
    for step in range(4):
        now += 50
        status = _decide(engine, victims, now)
    assert (status == int(Status.OVER_LIMIT)).all()

    # storm: 4x capacity of distinct never-seen keys, each exactly once
    for wave in range(8):
        now += 50
        storm = rng.integers(1, 1 << 63, size=cap // 2, dtype=np.uint64)
        s = _decide(engine, storm, now, limit=3)
        # fresh distinct keys must all admit (a refusal here would be
        # phantom OVER-refusal, the opposite failure mode)
        frac_admit = (s == int(Status.UNDER_LIMIT)).mean()
        assert frac_admit > 0.99, frac_admit

    # revisit: evicted victims re-admit (state loss), surviving ones
    # still refuse; none may answer anything but UNDER/OVER
    now += 50
    status = _decide(engine, victims, now)
    readmitted = (status == int(Status.UNDER_LIMIT)).mean()
    # the documented expectation: a 4x-capacity storm evicts most of the
    # store, so MOST victims re-admit; if this ever drops near zero the
    # eviction policy changed and the README contract must be revisited
    assert readmitted >= 0.5, readmitted


def test_capacity_storm_exports_counters_via_metrics():
    """The over-admission signals must reach the operator: a store at
    capacity silently sheds state, so dropped creates (way exhaustion
    within a batch) and evictions (occupied ways overwritten) must show
    up as nonzero store_dropped_creates_total / store_evictions_total in
    the /metrics exposition (reference exposes the analogous
    cache_size-vs-max pressure, cache/lru.go:56-59,164-176)."""
    import urllib.request

    from gubernator_tpu.cluster import LocalCluster
    from gubernator_tpu.serve.backends import TpuBackend
    from tests._util import free_ports

    p1, p2 = free_ports(2)
    grpc_addr = f"127.0.0.1:{p1}"
    http_addr = f"127.0.0.1:{p2}"
    # 16 ways x 8 buckets = 128 entries; a 1000-distinct-key batch puts
    # ~125 creates in every bucket: 16 fill the ways, the rest drop.
    # A second distinct batch then finds every way occupied: evictions.
    cluster = LocalCluster(
        [grpc_addr],
        backend_factory=lambda: TpuBackend(
            StoreConfig(rows=16, slots=8), buckets=(1024,)
        ),
        http_addresses=[http_addr],
    )
    cluster.start()
    try:
        from gubernator_tpu.client import V1Client

        rng = np.random.default_rng(0xCAFE)
        with V1Client(grpc_addr) as client:
            for wave in range(2):
                reqs = [
                    RateLimitReq(
                        name="storm",
                        unique_key=f"k{wave}-{i}-{rng.integers(1 << 30)}",
                        hits=1,
                        limit=1000,
                        duration=60_000,
                    )
                    for i in range(1000)
                ]
                client.get_rate_limits(reqs)

        body = urllib.request.urlopen(
            f"http://{http_addr}/metrics", timeout=10
        ).read().decode()
        got = {}
        for line in body.splitlines():
            for name in (
                "store_dropped_creates_total",
                "store_evictions_total",
            ):
                if line.startswith(name + " "):
                    got[name] = float(line.split()[1])
        assert got.get("store_dropped_creates_total", 0) > 0, body[:2000]
        assert got.get("store_evictions_total", 0) > 0, body[:2000]

        # engine-level cross-check: the counters came from the kernel's
        # packed stats, not an accident of the metrics layer
        snap = cluster.servers[0].backend.stats()
        assert snap["dropped"] > 0 and snap["evictions"] > 0
    finally:
        cluster.stop()
