"""Over-admission under eviction, characterized against the exact oracle
(BASELINE config 4's "bounded over-count"; VERDICT r1 weak #7).

The slot store's eviction contract: when a bucket's ways fill, the
entry with the earliest expiry is evicted, and a still-live evicted
window loses its consumed count — the key gets a fresh window on next
sight, briefly over-admitting (same contract as reference LRU eviction
/ restart state loss, architecture.md:5-11). This test MEASURES that
over-admission rate for zipf traffic at several store load factors vs
an unbounded exact oracle, and pins the bound the README advertises.
"""

import numpy as np
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    Status,
)
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.engine import TpuEngine
from gubernator_tpu.core.oracle import get_rate_limit
from gubernator_tpu.core.store import StoreConfig

T0 = 1_700_000_000_000


def _over_admission_rate(n_keys: int, capacity_cfg: StoreConfig,
                         steps: int = 30, batch: int = 512) -> float:
    """Fraction of requests the engine admits that the exact oracle
    (no eviction, infinite memory) would refuse."""
    engine = TpuEngine(capacity_cfg, buckets=(batch,))
    cache = LRUCache(1 << 30)  # effectively unbounded: the exact twin
    rng = np.random.default_rng(7)

    keys = [f"oa:{i}" for i in range(n_keys)]
    # Deterministic synthetic slot hashes instead of slot_hash_batch: the
    # native build hashes with XXH64, the fallback with blake2b, and the
    # pinned rates below must not depend on which one is loaded (bucket
    # collision patterns differ per hash function). The engine only needs
    # keys[i] <-> hashes_all[i] to be a stable injection.
    hashes_all = np.random.default_rng(11).integers(
        0, 1 << 63, size=n_keys, dtype=np.uint64
    ) << np.uint64(1) | np.uint64(1)

    over_admit = 0
    total = 0
    now = T0
    for step in range(steps):
        now += 50
        zipf = rng.zipf(1.3, size=batch) % n_keys
        kh = hashes_all[zipf]
        status, _, _, _ = engine.decide_arrays(
            kh,
            np.ones(batch, np.int64),
            np.full(batch, 10, np.int64),
            np.full(batch, 10_000_000, np.int64),
            np.zeros(batch, np.int32),
            np.zeros(batch, bool),
            now,
        )
        for i in range(batch):
            r = RateLimitReq(
                name="oa", unique_key=keys[zipf[i]], hits=1, limit=10,
                duration=10_000_000, algorithm=Algorithm.TOKEN_BUCKET,
            )
            want = get_rate_limit(cache, r, now=now)
            total += 1
            if (
                status[i] == int(Status.UNDER_LIMIT)
                and want.status == Status.OVER_LIMIT
            ):
                over_admit += 1
    return over_admit / total


@pytest.mark.parametrize(
    "n_keys,max_rate",
    [
        (400, 0.0),  # 39% load: exact behavior, zero over-admission
        (700, 0.0),  # 68% load: still exact
        (900, 0.01),  # 88% load: rare way-exhaustion evictions (~0.26%)
        (1300, 0.02),  # 127% load (over capacity): ~0.63%
        (2000, 0.04),  # 195% load: ~1.5%, still bounded
    ],
)
def test_over_admission_bounded(n_keys, max_rate):
    """Store: 16 ways x 64 buckets = 1024 entries (the production way
    geometry). Asserted bounds give the measured rates 2-3x headroom;
    the README performance table quotes the measured numbers.

    These rates depend on the ranked-empty-way writeback: before it,
    simultaneous fresh keys colliding in a bucket dropped all but one
    creation, measuring ~3% over-admission even at 39% load."""
    cfg = StoreConfig(rows=16, slots=64)
    rate = _over_admission_rate(n_keys, cfg)
    assert rate <= max_rate, (
        f"over-admission {rate:.4f} exceeds {max_rate} at {n_keys} keys"
    )
