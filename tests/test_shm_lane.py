"""Shared-memory GEB lane (r18): negotiation, frame transport,
fallback, drain semantics, and decision identity.

The lane carries the EXACT windowed frame bytes through
`FrameService.serve_frame_bytes`, so everything above the transport
(shed screen, stage clock, drain/GEBR refusals, response encoding) is
the TCP doors' by construction — these tests pin the transport layer:

- GEBM/GEBN negotiation happens only where it is sound (unix socket,
  shm-enabled service) and `shm='require'` fails closed elsewhere;
- frames ride the ring when they fit and fall back to the control
  socket (same connection, same window) when they don't;
- a drain answers every frame already in flight through the ring
  FIRST, then lands the GEBR and closes the lane (socket parity);
- the shm door decides byte-identically to the GEB-TCP string path
  under the r10 fake-clock fuzz (two fresh stacks, one stream).
"""

import asyncio
import struct

import numpy as np
import pytest

from _util import free_ports
from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.serve.edge_bridge import EdgeBridge
from gubernator_tpu.client_geb import (
    AsyncGebClient,
    GebDrainingError,
    GebError,
)

T0 = 1_700_000_000_000


class FakeClock:
    def __init__(self):
        self.t = T0

    def __call__(self):
        return self.t


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


class FakeInstance:
    """Echo server: UNDER_LIMIT with remaining = limit - hits."""

    async def get_rate_limits(self, reqs, stage_frame=False):
        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=r.limit - r.hits, reset_time=123,
            )
            for r in reqs
        ]


def _counter(metric) -> float:
    return metric._value.get()


def _req(key, hits=1, limit=9, duration=60_000):
    return RateLimitReq(
        name="shmlane", unique_key=key, hits=hits, limit=limit,
        duration=duration,
    )


def test_shm_negotiates_and_carries_frames(tmp_path):
    """The happy path: unix socket + shm-enabled bridge -> the lane
    maps, every small frame rides the ring (zero socket frames), and
    decisions come back correct and in order."""
    from gubernator_tpu.serve import metrics

    path = str(tmp_path / "b.sock")

    async def run():
        sessions0 = _counter(metrics.GEB_SHM_SESSIONS)
        frames0 = _counter(metrics.GEB_SHM_FRAMES)
        bridge = EdgeBridge(
            FakeInstance(), path, shm_enabled=True, shm_ring_kib=128
        )
        await bridge.start()
        client = AsyncGebClient(f"unix:{path}", shm="require")
        try:
            hello = await client.connect()
            assert hello.shm
            st = client.stats()
            assert st["transport"] == "shm"
            # pipelined batches complete out of order through the ring
            outs = await asyncio.gather(
                *[
                    client.get_rate_limits(
                        [_req(f"k{i}", hits=i % 3, limit=7)]
                    )
                    for i in range(20)
                ]
            )
            for i, resps in enumerate(outs):
                assert len(resps) == 1
                assert resps[0].status == Status.UNDER_LIMIT
                assert resps[0].remaining == 7 - (i % 3)
            st = client.stats()
            assert st["frames_shm"] == 20
            assert st["frames_socket"] == 0
            assert _counter(metrics.GEB_SHM_SESSIONS) == sessions0 + 1
            assert _counter(metrics.GEB_SHM_FRAMES) >= frames0 + 20
        finally:
            await client.close()
            await bridge.stop()

    asyncio.run(run())


def test_shm_refused_by_disabled_server(tmp_path):
    """A bridge without shm_enabled never advertises HELLO_SHM: auto
    clients ride the socket silently; 'require' fails closed."""
    path = str(tmp_path / "b.sock")

    async def run():
        bridge = EdgeBridge(FakeInstance(), path)  # shm off (default)
        await bridge.start()
        try:
            auto = AsyncGebClient(f"unix:{path}", shm="auto")
            hello = await auto.connect()
            assert not hello.shm
            resps = await auto.get_rate_limits([_req("a")])
            assert resps[0].status == Status.UNDER_LIMIT
            st = auto.stats()
            assert st["transport"] == "unix"
            assert st["frames_shm"] == 0 and st["frames_socket"] == 1
            await auto.close()

            hard = AsyncGebClient(f"unix:{path}", shm="require")
            with pytest.raises(GebError, match="no lane mapped"):
                await hard.connect()
            await hard.close()
        finally:
            await bridge.stop()

    asyncio.run(run())


def test_shm_never_negotiated_over_tcp(tmp_path):
    """HELLO_SHM is per-CONNECTION: the same shm-enabled bridge must
    not advertise (or grant) a lane to a TCP client — same-hostness is
    only proven by AF_UNIX."""
    path = str(tmp_path / "b.sock")
    (port,) = free_ports(1)

    async def run():
        bridge = EdgeBridge(
            FakeInstance(), path,
            tcp_address=f"127.0.0.1:{port}", shm_enabled=True,
        )
        await bridge.start()
        try:
            tcp = AsyncGebClient(f"127.0.0.1:{port}", shm="auto")
            hello = await tcp.connect()
            assert not hello.shm
            resps = await tcp.get_rate_limits([_req("t")])
            assert resps[0].status == Status.UNDER_LIMIT
            assert tcp.stats()["transport"] == "tcp"
            await tcp.close()

            hard = AsyncGebClient(f"127.0.0.1:{port}", shm="require")
            with pytest.raises(GebError, match="no lane mapped"):
                await hard.connect()
            await hard.close()
        finally:
            await bridge.stop()

    asyncio.run(run())


def test_oversized_frame_falls_back_to_socket(tmp_path):
    """A frame past the lane's bound (ring/4) must transparently ride
    the control socket — same connection, same credit window — while
    small frames keep using the ring."""
    path = str(tmp_path / "b.sock")

    async def run():
        # 64 KiB rings -> 16 KiB request bound
        bridge = EdgeBridge(
            FakeInstance(), path, shm_enabled=True, shm_ring_kib=64
        )
        await bridge.start()
        client = AsyncGebClient(f"unix:{path}", shm="require")
        try:
            await client.connect()
            small = await client.get_rate_limits([_req("s")])
            big = await client.get_rate_limits(
                [_req("b" * 30_000, limit=5)]
            )
            assert small[0].status == Status.UNDER_LIMIT
            assert big[0].status == Status.UNDER_LIMIT
            assert big[0].limit == 5
            st = client.stats()
            assert st["frames_shm"] == 1
            assert st["frames_socket"] == 1
        finally:
            await client.close()
            await bridge.stop()

    asyncio.run(run())


def test_shm_drain_answers_inflight_then_refuses(tmp_path):
    """Drain/GEBR parity on the ring: frames already in service when
    the drain starts are ANSWERED through the lane; a frame arriving
    mid-drain is refused with the GEBR drain code (GebDrainingError),
    and only that frame — no accepted frame is dropped."""
    path = str(tmp_path / "b.sock")

    class GatedInstance:
        def __init__(self):
            self.gate = asyncio.Event()
            self.entered = 0

        async def get_rate_limits(self, reqs, stage_frame=False):
            self.entered += 1
            await self.gate.wait()
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=r.limit,
                    remaining=r.limit - r.hits, reset_time=1,
                )
                for r in reqs
            ]

    async def run():
        inst = GatedInstance()
        bridge = EdgeBridge(
            inst, path, shm_enabled=True, shm_ring_kib=128
        )
        await bridge.start()
        client = AsyncGebClient(f"unix:{path}", shm="require")
        try:
            await client.connect()
            inflight = [
                asyncio.ensure_future(
                    client.get_rate_limits([_req(f"g{i}")])
                )
                for i in range(3)
            ]
            deadline = asyncio.get_running_loop().time() + 5
            while inst.entered < 3:
                assert asyncio.get_running_loop().time() < deadline, (
                    "gated frames never reached the instance"
                )
                await asyncio.sleep(0.005)
            assert client.stats()["frames_shm"] == 3

            drain_task = asyncio.ensure_future(bridge.drain(10.0))
            await asyncio.sleep(0.02)  # _draining is set
            late = asyncio.ensure_future(
                client.get_rate_limits([_req("late")])
            )
            await asyncio.sleep(0.05)  # the GEBR is parked on inflight
            inst.gate.set()

            outs = await asyncio.gather(*inflight)
            for resps in outs:
                assert resps[0].status == Status.UNDER_LIMIT
            with pytest.raises(GebDrainingError):
                await late
            await drain_task
        finally:
            await client.close()
            await bridge.stop()

    asyncio.run(run())


def _be():
    from gubernator_tpu.core.store import StoreConfig
    from gubernator_tpu.serve.backends import TpuBackend

    return TpuBackend(
        StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
    )


def _fuzz_stream(rng, keys, steps):
    for step in range(steps):
        n = int(rng.integers(1, 7))
        batch = []
        for _ in range(n):
            k = int(rng.integers(len(keys)))
            batch.append(
                RateLimitReq(
                    name="shmdoor",
                    unique_key=keys[k],
                    hits=int(rng.choice([0, 1, 1, 1, 2, 9])),
                    limit=int(rng.choice([1, 2, 3, 50])),
                    duration=int(rng.choice([400, 2000, 60_000])),
                    algorithm=Algorithm(k % 2),
                )
            )
        yield step, batch, int(rng.choice([0, 0, 1, 7, 150, 500, 2500]))


def test_shm_vs_tcp_string_identity_fuzz(monkeypatch, tmp_path):
    """Decision identity across the r18 transport: the shm door (fast
    frames through the ring) against the GEB-TCP string path, two
    fresh single-node stacks, one fake-clock fuzz stream — byte-equal
    (status, limit, remaining, reset_time, error) on every item."""
    from gubernator_tpu.cluster import LocalCluster

    clock = FakeClock()
    _pin_clock(monkeypatch, clock)
    path = str(tmp_path / "d.sock")

    ports = free_ports(3)
    clusters = [
        # stack 0: GEB-TCP string reference; stack 1: shm door
        LocalCluster(
            [f"127.0.0.1:{ports[0]}"], backend_factory=_be,
            geb_ports=[ports[2]],
        ),
        LocalCluster([f"127.0.0.1:{ports[1]}"], backend_factory=_be),
    ]
    for c in clusters:
        c.start()
        inst = c.servers[0].instance
        if inst.shed is not None:
            inst.shed.now_fn = clock

    async def _bridge_up():
        bridge = EdgeBridge(
            clusters[1].servers[0].instance, path,
            shm_enabled=True, shm_ring_kib=256,
        )
        await bridge.start()
        return bridge

    bridge = clusters[1].run(_bridge_up())
    try:

        async def run():
            string = AsyncGebClient(
                f"127.0.0.1:{ports[2]}", mode="string", shm="off"
            )
            shm = AsyncGebClient(f"unix:{path}", shm="require")
            rng = np.random.default_rng(47)
            keys = [f"sk{i}" for i in range(12)]
            try:
                await shm.connect()
                # the exercise: fast frames through the mapped ring
                assert shm._use_fast
                assert shm.stats()["transport"] == "shm"
                for step, batch, dt in _fuzz_stream(rng, keys, 70):
                    clock.t += dt
                    a = await string.get_rate_limits(batch)
                    b = await shm.get_rate_limits(batch)
                    for i, (x, y) in enumerate(zip(a, b)):
                        tx = (int(x.status), x.limit, x.remaining,
                              x.reset_time, x.error)
                        ty = (int(y.status), y.limit, y.remaining,
                              y.reset_time, y.error)
                        assert tx == ty, (step, i, batch[i], tx, ty)
                assert shm.stats()["frames_shm"] > 0
            finally:
                await string.close()
                await shm.close()

        asyncio.run(run())
    finally:
        clusters[1].run(bridge.stop())
        for c in clusters:
            c.stop()
