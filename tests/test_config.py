"""Config layer tests (env parsing, config file injection, validation)."""

import pytest

from gubernator_tpu.serve.config import (
    BehaviorConfig,
    ServerConfig,
    config_from_env,
    load_config_file,
)


def test_defaults_match_reference():
    # reference config.go:59-75; batch_wait=0 is a documented divergence
    # (drain-while-busy batching, see serve/config.py BehaviorConfig)
    b = BehaviorConfig()
    assert b.batch_timeout == 0.5
    assert b.batch_wait == 0.0
    assert b.batch_limit == 1000
    assert b.global_timeout == 0.5
    assert b.global_sync_wait == 0.0005
    assert b.global_batch_limit == 1000


def test_env_parsing():
    env = {
        "GUBER_GRPC_ADDRESS": "0.0.0.0:1234",
        "GUBER_HTTP_ADDRESS": "0.0.0.0:1235",
        "GUBER_BATCH_WAIT_MS": "2",
        "GUBER_BATCH_LIMIT": "500",
        "GUBER_PEERS": "a:1, b:2 ,c:3",
        "GUBER_BACKEND": "exact",
        "GUBER_CACHE_SIZE": "123",
        "GUBER_DEBUG": "true",
    }
    conf = config_from_env(env)
    assert conf.grpc_address == "0.0.0.0:1234"
    assert conf.behaviors.batch_wait == 0.002
    assert conf.behaviors.batch_limit == 500
    assert conf.peers == ["a:1", "b:2", "c:3"]
    assert conf.backend == "exact"
    assert conf.cache_size == 123
    assert conf.debug is True


def test_batch_limit_cap():
    with pytest.raises(ValueError):
        config_from_env({"GUBER_BATCH_LIMIT": "5000"})


def test_etcd_k8s_mutual_exclusion():
    with pytest.raises(ValueError):
        config_from_env(
            {
                "GUBER_ETCD_ENDPOINTS": "localhost:2379",
                "GUBER_K8S_ENDPOINTS_SELECTOR": "app=x",
            }
        )


def test_config_file_injection(tmp_path):
    # reference cmd/gubernator/config.go:239-267
    f = tmp_path / "test.conf"
    f.write_text(
        "# comment\n"
        "\n"
        "GUBER_GRPC_ADDRESS=127.0.0.1:7777\n"
        "GUBER_BACKEND = exact \n"
    )
    env = load_config_file(str(f), env={})
    conf = config_from_env(env)
    assert conf.grpc_address == "127.0.0.1:7777"
    assert conf.backend == "exact"


def test_config_file_malformed(tmp_path):
    f = tmp_path / "bad.conf"
    f.write_text("not a kv line\n")
    with pytest.raises(ValueError):
        load_config_file(str(f), env={})


def test_buckets_follow_device_batch_limit():
    """A GUBER_DEVICE_BATCH_LIMIT above the default 4096 bucket ladder
    must extend the engine's padding buckets, or the first coalesced
    batch above 4096 would crash choose_bucket at runtime."""
    from gubernator_tpu.core.engine import buckets_for_limit
    from gubernator_tpu.core.engine import choose_bucket

    assert buckets_for_limit(1000) == (64, 256, 1024)
    b = buckets_for_limit(10_000)
    assert choose_bucket(sorted(b), 10_000) == 10_112  # 10_000 up to x128
    b = buckets_for_limit(16_384)
    assert choose_bucket(sorted(b), 16_384) == 16_384
    # a limit between rungs becomes its own final rung instead of padding
    # to the next power-of-four (ADVICE r1: 5000 used to pad 3.3x to 16384)
    b = buckets_for_limit(5000)
    assert b == (64, 256, 1024, 4096, 5120)
    assert choose_bucket(sorted(b), 4500) == 5120


def test_deep_buckets_extend_ladder():
    """Throughput-mode limits pick up the DEEP_BUCKETS rungs so a lull
    between 4096 and the top rung doesn't pad 26x."""
    from gubernator_tpu.core.engine import buckets_for_limit

    assert buckets_for_limit(131_072) == (
        64, 256, 1024, 4096, 16384, 32768, 131072,
    )
    assert buckets_for_limit(32_768) == (64, 256, 1024, 4096, 16384, 32768)
    # the default envelope is untouched: no deep rung below 16384
    assert buckets_for_limit(4096) == (64, 256, 1024, 4096)


def test_device_batch_limit_cross_validated_against_ladder():
    """GUBER_DEVICE_BATCH_LIMIT below the largest group the serving tier
    can enqueue (per-RPC cap / batch_limit / global_batch_limit) used to
    be accepted silently and crash choose_bucket at runtime; it must
    fail at boot with the knobs named."""
    with pytest.raises(ValueError, match="GUBER_DEVICE_BATCH_LIMIT"):
        config_from_env({"GUBER_DEVICE_BATCH_LIMIT": "500"})
    # global broadcasts ride the same batcher queue: a global_batch_limit
    # past the ladder top must fail too
    with pytest.raises(ValueError, match="GUBER_GLOBAL_BATCH_LIMIT"):
        config_from_env(
            {
                "GUBER_GLOBAL_BATCH_LIMIT": "5000",
                "GUBER_DEVICE_BATCH_LIMIT": "2000",
            }
        )
    # the exact backend has no bucket ladder: the same knobs pass
    conf = config_from_env(
        {"GUBER_DEVICE_BATCH_LIMIT": "500", "GUBER_BACKEND": "exact"}
    )
    assert conf.device_batch_limit == 500
    # a deep ladder covering the caps is accepted
    conf = config_from_env({"GUBER_DEVICE_BATCH_LIMIT": "131072"})
    assert conf.device_batch_limit == 131072


def test_deep_batch_knob():
    conf = config_from_env({"GUBER_DEVICE_DEEP_BATCH": "1"})
    assert conf.device_deep_batch is True
    assert config_from_env({}).device_deep_batch is False
    # deep batching is a device-batcher mode; exact decides inline
    with pytest.raises(ValueError, match="DEEP_BATCH"):
        config_from_env(
            {"GUBER_DEVICE_DEEP_BATCH": "1", "GUBER_BACKEND": "exact"}
        )


def test_store_footprint_pins_are_exclusive():
    with pytest.raises(ValueError, match="GUBER_STORE_MIB"):
        config_from_env(
            {"GUBER_STORE_MIB": "512", "GUBER_STORE_SLOTS": "32768"}
        )
    # MIB=0 means "off", not a pin: no conflict with explicit slots
    conf = config_from_env(
        {"GUBER_STORE_MIB": "0", "GUBER_STORE_SLOTS": "32768"}
    )
    assert conf.store_config().slots == 32768
    # target_keys + explicit slots is allowed: the budget lints the
    # explicit footprint at boot instead of overriding it
    conf = config_from_env(
        {"GUBER_STORE_TARGET_KEYS": "100000", "GUBER_STORE_SLOTS": "32768"}
    )
    assert conf.store_slots == 32768
    assert conf.store_target_keys == 100_000


def test_edge_env_knobs_parse():
    from gubernator_tpu.serve.config import config_from_env

    conf = config_from_env(
        {
            "GUBER_EDGE_TCP": "0.0.0.0:9470",
            "GUBER_EDGE_PEER_BRIDGES": "10.0.0.2:81=10.0.0.2:9470",
            "GUBER_EDGE_FAST": "No",
        }
    )
    assert conf.edge_tcp == "0.0.0.0:9470"
    assert conf.edge_peer_bridges == "10.0.0.2:81=10.0.0.2:9470"
    # the kill switch accepts the common falsy spellings (0/false/no/
    # off, any case) — an operator's "No" mid-incident must not
    # silently leave the fast path on
    assert conf.edge_fast is False
    assert config_from_env({}).edge_fast is True


def test_malformed_peer_bridges_fails_server_start():
    """A typo'd GUBER_EDGE_PEER_BRIDGES entry must abort startup with
    the offending entry named, not silently serve with a broken map."""
    import asyncio

    import pytest

    from gubernator_tpu.serve.config import config_from_env
    from gubernator_tpu.serve.server import Server

    conf = config_from_env(
        {
            "GUBER_BACKEND": "exact",
            "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
            "GUBER_HTTP_ADDRESS": "",
            "GUBER_EDGE_SOCKET": "/tmp/guber-badmap-test.sock",
            "GUBER_EDGE_PEER_BRIDGES": "10.0.0.2:81-no-equals",
        }
    )

    async def run():
        server = Server(conf)
        with pytest.raises(ValueError, match="no-equals"):
            await server.start()

    asyncio.run(run())
