"""Elastic ring rescale (r17, serve/rescale.py): ownership_diff ring
semantics, the tracked/pending tables, double-serve routing, the
ON==OFF differential identity guarantee through the real serving
pipeline (flat AND the simulated 8-device mesh), an in-process
add-node/remove-node handoff cycle over real gRPC (a tracked over-limit
key never under-admits), the ring-flip-mid-flush replication fix, the
post-reshuffle standby purge, and the GUBER_SHARDS store re-partition
identity (export -> install under a new ShardingPolicy).
"""

import asyncio

import grpc
import numpy as np
import pytest

from gubernator_tpu.api.grpc_glue import add_peers_servicer
from gubernator_tpu.api.types import (
    Algorithm,
    PeerInfo,
    RateLimitReq,
    Status,
    millisecond_now,
)
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve import metrics
from gubernator_tpu.serve.backends import (
    ExactBackend,
    MeshBackend,
    TpuBackend,
)
from gubernator_tpu.serve.config import BehaviorConfig, ServerConfig
from gubernator_tpu.serve.instance import Instance
from gubernator_tpu.serve.peers import ConsistentHashPicker, PeerClient
from gubernator_tpu.serve.rescale import RescaleManager

from tests.test_replication import (  # noqa: F401 (shared rig)
    FakeClock,
    _assert_same,
    _fuzz_stream,
    _pin_clock,
    _snap,
)

ADDR = "127.0.0.1:1"
T0 = 1_700_000_000_000


def _req(key, hits=1, limit=5, duration=60_000,
         algo=Algorithm.TOKEN_BUCKET):
    return RateLimitReq(
        name="resc", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo,
    )


def _picker(hosts, me=None):
    p = ConsistentHashPicker()
    for h in hosts:
        p.add(PeerClient(BehaviorConfig(), h, is_owner=(h == me)))
    return p


def _counter(metric, **labels) -> float:
    m = metric.labels(**labels) if labels else metric
    return m._value.get()


# -- ownership_diff ---------------------------------------------------------


def test_ownership_diff_pins_ring_semantics():
    """The diff is exactly the set of self-owned keys the new ring
    routes elsewhere, grouped by their NEW owner — and the new owner is
    the new ring's get(), nothing else."""
    me = "10.0.0.1:81"
    hosts = [f"10.0.0.{i}:81" for i in range(1, 5)]
    old = _picker(hosts, me=me)
    keys = [f"od{i}" for i in range(400)]
    # crc32 placement: not every joining host cuts THIS node's arc —
    # roll candidate joiners until one takes over part of it
    for j in range(9, 40):
        new = _picker(hosts[:3] + [f"10.0.0.{j}:81"], me=me)
        if old.ownership_diff(new, keys):
            break
    diff = old.ownership_diff(new, keys)
    moved = {k for _, (_, ks) in diff.items() for k in ks}
    for k in keys:
        owned_old = old.get(k).is_owner
        new_owner = new.get(k)
        if owned_old and not new_owner.is_owner:
            assert k in moved
            assert k in dict([
                (kk, None) for kk in diff[new_owner.host][1]
            ])
        else:
            assert k not in moved
    assert moved, "no key moved in 400 draws — ring fixture broken"
    # the grouped client IS the new picker's client for that host
    for host, (peer, _ks) in diff.items():
        assert peer is new.get_peer_by_host(host)
    # identical rings diff to nothing
    assert old.ownership_diff(old, keys) == {}
    # empty rings diff to nothing rather than raising
    assert ConsistentHashPicker().ownership_diff(new, keys) == {}
    assert old.ownership_diff(ConsistentHashPicker(), keys) == {}


# -- manager tables ---------------------------------------------------------


class _DummyInstance:
    pass


def _mgr(**conf_kw) -> RescaleManager:
    conf = ServerConfig(
        grpc_address=ADDR, advertise_address=ADDR, rescale=True,
        **conf_kw,
    )
    return RescaleManager(conf, _DummyInstance())


def test_note_owned_gates_and_freshest_kept_eviction():
    m = _mgr(rescale_track_keys=2)
    m.note_owned(_req("a", hits=0))  # peek: cannot create a window
    m.note_owned(_req("b", algo=Algorithm.LEAKY_BUCKET))  # out of scope
    assert m.tracked_len == 0
    before = _counter(metrics.RESCALE_DROPPED, what="track_evict")
    m.note_owned(_req("a"))
    m.note_owned(_req("b"))
    m.note_owned(_req("a", limit=9))  # re-touch refreshes (limit 9)
    m.note_owned(_req("c"))  # at capacity: "b" (stalest touch) evicts
    assert sorted(m._tracked) == sorted(
        [_req("a").hash_key(), _req("c").hash_key()]
    )
    assert m._tracked[_req("a").hash_key()][1] == 9
    assert _counter(
        metrics.RESCALE_DROPPED, what="track_evict"
    ) == before + 1


def test_note_owned_fields_bridge_tier():
    m = _mgr()
    keys = ["a", "b", "c", "d"]
    fields = dict(
        hits=np.array([1, 0, 2, 1], np.int64),
        limit=np.array([5, 5, 7, 5], np.int64),
        duration=np.full(4, 60_000, np.int64),
        algo=np.array([0, 0, 0, 1], np.int32),
    )
    m.note_owned_fields(keys, fields)
    # b is a peek and d is leaky: ineligible
    assert sorted(m._tracked) == ["a", "c"]
    assert m._tracked["c"][1] == 7


def test_pending_install_lww_bound_pop_and_purge():
    async def run():
        m = _mgr(rescale_track_keys=2)

        class _Inst:
            def get_peer(self, key):
                raise RuntimeError("not owned")

        m.instance = _Inst()
        now = millisecond_now()
        newer = _snap("k1", remaining=1, reset_time=now + 9000, now=now)
        older = _snap("k1", remaining=3, reset_time=now + 4000, now=now)
        await m.install("o:1", [newer])
        await m.install("o:1", [older])  # LWW: older loses
        assert m._pending["k1"].remaining == 1
        await m.install("o:1", [newer])  # duplicate: idempotent no-op
        assert m.pending_len == 1
        await m.install("o:1", [_snap("k2", now=now),
                                _snap("k3", now=now)])
        assert m.pending_len == 2  # bounded: stalest evicted
        # expired snapshots are refused outright
        await m.install("o:1", [_snap("k4", reset_time=now - 1, now=now)])
        assert "k4" not in m._pending
        # pop is one-shot and expiry-gated
        assert m.pending_pop("k3") is not None
        assert m.pending_pop("k3") is None
        # an owner broadcast supersedes a parked handoff
        await m.install("o:1", [_snap("k5", now=now)])
        m.pending_purge(["k5"])
        assert m.pending_pop("k5") is None

    asyncio.run(run())


def test_route_override_double_serve_window():
    me = "10.0.0.1:81"
    hosts = [f"10.0.0.{i}:81" for i in range(1, 5)]
    old = _picker(hosts, me=me)
    new = _picker(hosts[:3] + ["10.0.0.9:81"], me=me)
    m = _mgr(rescale_double_serve=60.0)
    m.note_ring_change(old, new)
    keys = [f"ov{i}" for i in range(300)]
    routed = local = 0
    for k in keys:
        r = _req(k, hits=0)
        ov = m.route_override(k, r)
        o, n = old.get(k), new.get(k)
        if o.host == n.host or n.is_owner:
            assert ov is None  # unmoved, or we ARE the new owner
        elif o.is_owner:
            # this node is the OLD owner: serve locally (the returned
            # client is the live self client) and count + re-dirty
            assert ov is o and ov.is_owner
            local += 1
        elif o.host not in {p.host for p in new.peers()}:
            assert ov is None  # old owner left the ring: no stand-in
        else:
            assert ov is not None and ov.host == o.host
            routed += 1
    assert routed, "no moved key in 300 draws — ring fixture broken"
    # a closed window stops overriding and retires the transition
    m._transition = (old, new, 0.0)
    assert m.route_override(keys[0], _req(keys[0])) is None
    assert m._transition is None


def test_failed_reconcile_retries_until_delivered():
    """A moved key whose handoff send FAILS for the whole double-serve
    window must stay in the moved/tracked tables and keep retrying
    every tick — dropping it would strand the window on this node
    forever (a later ring change's diff cannot re-move it), the exact
    amnesia the subsystem exists to prevent."""

    async def run():
        m = _mgr(rescale_double_serve=0.0)  # window already closed
        key = "stranded"
        reset = millisecond_now() + 60_000

        class _Peer:
            host = "10.0.0.2:81"
            is_owner = False
            fail = True
            sent = []

            async def replicate_buckets(self, snaps, owner=""):
                if self.fail:
                    raise ConnectionError("door not ready")
                self.sent.extend(s.key for s in snaps)

        peer = _Peer()

        class _Backend:
            inline_decide = True

            def snapshot_read(self, keys, now=None):
                return [(5, 60_000, 0, reset, True) for _ in keys]

        class _Inst:
            backend = _Backend()

            def get_peer(self, k):
                return peer

        m.instance = _Inst()
        m._tracked[key] = (0, 5, 60_000)
        m._moved[key] = (0, 5, 60_000)
        await m.flush_once()  # send fails: nothing may retire
        assert key in m._moved and key in m._tracked
        peer.fail = False
        await m.flush_once()  # delivered: now it retires
        assert peer.sent == [key]
        assert key not in m._moved and key not in m._tracked

    asyncio.run(run())


def test_flap_returned_key_stays_tracked():
    """A moved key the ring gives BACK to this node mid-window leaves
    the moved set but remains tracked — it is a live owned window
    again and must ride the NEXT ring change."""

    async def run():
        m = _mgr(rescale_double_serve=0.0)

        class _Self:
            host = ADDR
            is_owner = True

        class _Inst:
            def get_peer(self, k):
                return _Self()

        m.instance = _Inst()
        m._tracked["back"] = (0, 5, 60_000)
        m._moved["back"] = (0, 5, 60_000)
        await m.flush_once()
        assert "back" not in m._moved
        assert "back" in m._tracked

    asyncio.run(run())


def test_drain_ships_pending_snapshots():
    """A draining node forwards its PARKED pending snapshots (windows
    handed to it whose first owned touch never came) to the
    ring-minus-self owners — they must not die with the process."""

    async def run():
        m = _mgr()
        other = PeerClient(BehaviorConfig(), "10.0.0.2:81")
        sent = []

        async def record(snaps, owner=""):
            sent.extend(s.key for s in snaps)

        other.replicate_buckets = record
        picker = ConsistentHashPicker()
        picker.add(PeerClient(BehaviorConfig(), ADDR, is_owner=True))
        picker.add(other)

        class _Inst:
            pass

        inst = _Inst()
        inst.picker = picker

        class _Backend:
            inline_decide = True

            def snapshot_read(self, keys, now=None):
                return [None for _ in keys]  # nothing tracked-live

        inst.backend = _Backend()
        m.instance = inst
        now = millisecond_now()
        m._pending["pk1"] = _snap("pk1", reset_time=now + 60_000,
                                  now=now)
        m._pending["expired"] = _snap("expired", reset_time=now - 1,
                                      now=now)
        await m.drain()
        assert sent == ["pk1"]  # live pending forwarded, expired not

    asyncio.run(run())


# -- differential identity: rescale ON == OFF on a static ring --------------


def _conf(backend="exact", **kw) -> ServerConfig:
    conf = ServerConfig(
        grpc_address=ADDR,
        advertise_address=ADDR,
        backend=backend,
        rescale=True,
        replication_sync_wait=60.0,  # flushes driven manually
        behaviors=BehaviorConfig(
            peer_timeout=0.2, peer_retries=0, peer_backoff=0.001,
            peer_backoff_max=0.002, breaker_failures=3,
            breaker_cooldown=0.2,
        ),
    )
    for k, v in kw.items():
        setattr(conf, k, v)
    return conf


async def _fuzz_pair(mk_backend, clock, steps, seed):
    """ON and OFF twins on an identical STATIC 2-host ring; only the
    GUBER_RESCALE knob differs, and only self-owned keys are driven —
    the static-ring identity contract. The manager's flush loop runs
    (manually ticked) and must act on nothing."""
    from tests._util import free_ports

    def owned(dead_addr, count=200):
        picker = ConsistentHashPicker()
        mecl = PeerClient(BehaviorConfig(), ADDR, is_owner=True)
        picker.add(mecl)
        picker.add(PeerClient(BehaviorConfig(), dead_addr))
        return [
            f"f{i}" for i in range(count)
            # the shared _fuzz_stream issues name="replfuzz" requests;
            # the ownership screen must hash the same keys
            if picker.get(
                RateLimitReq(
                    name="replfuzz", unique_key=f"f{i}"
                ).hash_key()
            ) is mecl
        ]

    for port in free_ports(16):
        dead = f"127.0.0.1:{port}"
        keys = owned(dead)[:12]
        if len(keys) >= 8:
            break
    assert len(keys) >= 8, "no workable ring split in 16 rolls"

    async def mk(rescale):
        conf = _conf(rescale=rescale)
        inst = Instance(conf, mk_backend())
        inst.start()
        await inst.set_peers([
            PeerInfo(address=ADDR, is_owner=True),
            PeerInfo(address=dead, is_owner=False),
        ])
        return inst

    on = await mk(True)
    off = await mk(False)
    if on.shed is not None:
        on.shed.now_fn = clock
        off.shed.now_fn = clock
    try:
        rng = np.random.default_rng(seed)
        for step, batch, dt in _fuzz_stream(rng, keys, steps):
            clock.t += dt
            a = await on.get_rate_limits(batch)
            b = await off.get_rate_limits(batch)
            for x, y, r in zip(a, b, batch):
                _assert_same(x, y, (step, r))
            if step % 25 == 24:
                await on.rescale.flush_once()  # static ring: a no-op
        assert on.rescale.tracked_len > 0, "fuzz never tracked a window"
        assert on.rescale.pending_len == 0
    finally:
        await on.stop()
        await off.stop()


@pytest.mark.parametrize("seed", [3, 11])
def test_differential_identity_fuzz_exact(monkeypatch, seed):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)
    asyncio.run(_fuzz_pair(lambda: ExactBackend(10_000), clock, 250, seed))


def test_differential_identity_fuzz_device(monkeypatch):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    def be():
        return TpuBackend(StoreConfig(rows=16, slots=1 << 10),
                          buckets=(16, 64))

    asyncio.run(_fuzz_pair(be, clock, 100, 5))


def test_differential_identity_fuzz_mesh(monkeypatch):
    """The same ON==OFF identity through the 8-device simulated mesh
    (instance -> batcher -> arrival prep -> merged submit -> shard_map
    dispatch): the rescale tracked set is host state only and the
    snapshot surface is non-mutating on the sharded store too."""
    import jax

    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    def be():
        return MeshBackend(
            StoreConfig(rows=4, slots=256),
            devices=jax.devices(),
            buckets=(16, 64),
        )

    asyncio.run(_fuzz_pair(be, clock, 60, 7))


# -- add-node / remove-node handoff cycle over real gRPC --------------------


def test_add_remove_node_handoff_never_under_admits():
    """The tentpole end-to-end, in-process and replication-OFF (the
    subsystem stands alone): drive a key over-limit on its owner, ADD a
    node the ring elects as its new owner, hand off, and the key stays
    over-limit on the new owner with the SAME window; then REMOVE the
    node and the key is still over-limit back on the original ring —
    never a fresh (under-admitting) window anywhere in the cycle."""
    from tests._util import free_ports
    from gubernator_tpu.serve.server import PeersV1Servicer

    async def serve(inst, addr):
        server = grpc.aio.server()
        add_peers_servicer(server, PeersV1Servicer(inst))
        assert server.add_insecure_port(addr) != 0
        await server.start()
        return server

    def roll_addresses():
        """Ports + a key that A owns on the 2-ring and C owns on the
        3-ring; crc32 placement makes some port draws keyless, so
        re-roll instead of StopIterating."""
        for _ in range(16):
            pa, pb, pc = free_ports(3)
            addrs = [f"127.0.0.1:{p}" for p in (pa, pb, pc)]
            ring2 = _picker(addrs[:2], me=addrs[0])
            ring3 = _picker(addrs, me=addrs[0])
            for i in range(512):
                kh = _req(f"hk{i}").hash_key()
                if (
                    ring2.get(kh).is_owner
                    and ring3.get(kh).host == addrs[2]
                ):
                    return addrs, f"hk{i}"
        raise AssertionError("no A->C moving key in 16 port rolls")

    async def run():
        (addr_a, addr_b, addr_c), key = roll_addresses()

        def conf_for(me):
            c = _conf()
            c.grpc_address = me
            c.advertise_address = me
            return c

        async def boot(me, members):
            inst = Instance(conf_for(me), ExactBackend(1000))
            inst.start()
            await inst.set_peers([
                PeerInfo(address=h, is_owner=(h == me))
                for h in members
            ])
            return inst, await serve(inst, me)

        two = [addr_a, addr_b]
        three = [addr_a, addr_b, addr_c]
        a, srv_a = await boot(addr_a, two)
        b, srv_b = await boot(addr_b, two)
        c = srv_c = None
        try:
            r = (await a.get_rate_limits([_req(key, hits=9, limit=5)]))[0]
            assert r.error == "" and r.status == Status.OVER_LIMIT
            reset_time = r.reset_time
            assert _req(key).hash_key() in a.rescale._tracked

            # scale OUT: C joins; every node learns the new membership
            # (C first, so the handoff install lands owned)
            c, srv_c = await boot(addr_c, three)
            for node, me in ((a, addr_a), (b, addr_b)):
                await node.set_peers([
                    PeerInfo(address=h, is_owner=(h == me))
                    for h in three
                ])
            moved_before = _counter(metrics.RESCALE_KEYS_MOVED)
            await a.rescale.flush_once()
            assert _counter(metrics.RESCALE_KEYS_MOVED) > moved_before

            # the NEW owner answers the SAME frozen window: over-limit,
            # zero remaining, the original reset_time — no amnesia
            r = (await c.get_rate_limits([_req(key, hits=0, limit=5)]))[0]
            assert r.error == ""
            assert r.status == Status.OVER_LIMIT, (
                "quota amnesia on scale-out: the new owner opened a "
                "fresh window"
            )
            # created-over windows keep remaining == limit (the
            # reference's sticky-over semantics); the frozen refusal
            # and its ORIGINAL reset survive the move
            assert r.remaining == 5 and r.reset_time == reset_time
            # and through a forwarding peer (normal routing, post-flip)
            a.rescale._transition = None  # close the double-serve window
            r = (await a.get_rate_limits([_req(key, hits=0, limit=5)]))[0]
            assert r.error == "" and r.status == Status.OVER_LIMIT

            # scale IN: C leaves; C's own ring change ships its owned
            # windows back to the 2-ring owners before it goes
            for node, me in ((a, addr_a), (b, addr_b), (c, addr_c)):
                await node.set_peers([
                    PeerInfo(address=h, is_owner=(h == me))
                    for h in two
                ])
            await c.rescale.flush_once()
            r = (await a.get_rate_limits([_req(key, hits=0, limit=5)]))[0]
            assert r.error == ""
            assert r.status == Status.OVER_LIMIT, (
                "quota amnesia on scale-in: the returning owner opened "
                "a fresh window"
            )
            assert r.remaining == 5 and r.reset_time == reset_time
        finally:
            await srv_a.stop(None)
            await srv_b.stop(None)
            if srv_c is not None:
                await srv_c.stop(None)
            await a.stop()
            await b.stop()
            if c is not None:
                await c.stop()

    asyncio.run(run())


# -- satellites: replication under a ring flip ------------------------------


def test_replication_flush_resolves_successor_post_flip():
    """Ring-flip-mid-flush (r17 satellite): a membership change landing
    while the snapshot gather is in flight must re-resolve successors
    against the POST-change ring — the pre-change successor receives
    nothing."""
    from gubernator_tpu.serve.replication import ReplicationManager

    async def run():
        conf = _conf()
        conf.replication = True
        inst = Instance(conf, ExactBackend(1000))
        inst.start()
        hosts = [ADDR, "10.0.0.2:81", "10.0.0.3:81"]
        await inst.set_peers([
            PeerInfo(address=h, is_owner=(h == ADDR)) for h in hosts
        ])
        repl = inst.repl
        sent = {}

        async def record(self, snaps, owner=""):
            sent.setdefault(self.host, []).extend(s.key for s in snaps)

        for p in inst.picker.peers():
            p.replicate_buckets = record.__get__(p)
        try:
            # a self-owned key whose successor DIFFERS between the
            # 3-ring and the 2-ring without its current successor
            key = None
            for i in range(512):
                k = _req(f"ff{i}").hash_key()
                if not inst.get_peer(k).is_owner:
                    continue
                succ3 = inst.picker.get_successor(k).host
                ring2 = _picker(
                    [h for h in hosts if h != succ3], me=ADDR
                )
                if ring2.get_successor(k).host != succ3:
                    key, old_succ = k, succ3
                    new_succ = ring2.get_successor(k).host
                    survivors = [h for h in hosts if h != succ3]
                    break
            assert key is not None, "no successor-flipping key found"

            await inst.get_rate_limits(
                [_req(f"ff{i}") for i in range(512)
                 if _req(f"ff{i}").hash_key() == key]
            )
            assert key in repl._dirty

            # the flip lands while the flush's snapshot gather is in
            # flight (the await point a device read would park on)
            orig = repl._snapshot

            async def snapshot_then_flip(metas):
                snaps = await orig(metas)
                await inst.set_peers([
                    PeerInfo(address=h, is_owner=(h == ADDR))
                    for h in survivors
                ])
                # re-stub the rebuilt ring's clients
                for p in inst.picker.peers():
                    p.replicate_buckets = record.__get__(p)
                return snaps

            repl._snapshot = snapshot_then_flip
            await repl.flush_once()
            assert key in sent.get(new_succ, []), (
                f"snapshot not shipped to the post-flip successor "
                f"({sent})"
            )
            assert key not in sent.get(old_succ, []), (
                "snapshot shipped to the PRE-flip successor"
            )
        finally:
            await inst.stop()

    asyncio.run(run())


def test_standby_purged_when_no_longer_successor():
    """Post-reshuffle standby hygiene (r17 satellite): rows for keys
    this node neither owns nor succeeds on the new ring are dropped
    (they could otherwise seed a WRONG takeover window later); rows it
    still succeeds — or now owns — survive."""
    async def run():
        conf = _conf()
        conf.replication = True
        inst = Instance(conf, ExactBackend(1000))
        inst.start()
        hosts = [ADDR, "10.0.0.2:81", "10.0.0.3:81", "10.0.0.4:81"]
        await inst.set_peers([
            PeerInfo(address=h, is_owner=(h == ADDR)) for h in hosts
        ])
        try:
            repl = inst.repl
            now = millisecond_now()
            # park standby rows for keys of EVERY succession class
            keys = [f"sp{i}" for i in range(256)]
            for k in keys:
                repl._standby[k] = _snap(k, reset_time=now + 60_000,
                                         now=now)
            # reshuffle: one non-self host leaves
            survivors = hosts[:2] + hosts[3:]
            await inst.set_peers([
                PeerInfo(address=h, is_owner=(h == ADDR))
                for h in survivors
            ])
            # set_peers already purged (the Instance hook); verify the
            # invariant the purge pins
            for k in list(repl._standby):
                own = inst.get_peer(k).is_owner
                succ = inst.picker.get_successor(k)
                assert own or (succ is not None and succ.is_owner), (
                    f"stale standby row survived for '{k}'"
                )
            purged = set(keys) - set(repl._standby)
            assert purged, "reshuffle purged nothing — fixture broken"
            for k in purged:
                own = inst.get_peer(k).is_owner
                succ = inst.picker.get_successor(k)
                assert not (
                    own or (succ is not None and succ.is_owner)
                ), f"purge dropped a row this node still covers ('{k}')"
        finally:
            await inst.stop()

    asyncio.run(run())


# -- GUBER_SHARDS re-partition identity -------------------------------------


def _drive_windows(be, n=64, now=T0):
    """Mixed live token windows: under, exhausted, created-over
    (sticky), plus a leaky entry (since r19 it migrates too, flags
    lane and all; snapshot_read still excludes it by scope)."""
    reqs = []
    for i in range(n):
        kind = i % 4
        reqs.append(RateLimitReq(
            name="rp", unique_key=f"rp{i}",
            hits=(2, 5, 9, 1)[kind],
            limit=(10, 5, 5, 10)[kind],
            duration=60_000,
            algorithm=(
                Algorithm.LEAKY_BUCKET if kind == 3
                else Algorithm.TOKEN_BUCKET
            ),
        ))
    be.decide(reqs, [False] * n, now=now)
    return [r.hash_key() for r in reqs]


def _rows_mod_duration(rows):
    """snapshot_read rows with the duration column dropped. Kept for
    the GLOBAL-replica install comparisons (upsert_globals without the
    r19 full lanes does not persist duration); the re-partition path
    now round-trips duration too, so those tests compare full rows."""
    return [
        None if r is None else (r[0], r[2], r[3], r[4]) for r in rows
    ]


def test_repartition_flat_to_mesh_preserves_every_window():
    import jax

    from gubernator_tpu.parallel.policy import ShardingPolicy

    flat = TpuBackend(StoreConfig(rows=4, slots=256), buckets=(64,))
    keys = _drive_windows(flat)
    mesh_engine = flat.engine.repartition(
        ShardingPolicy.over_mesh(jax.devices()), now=T0 + 5
    )
    a = flat.snapshot_read(keys, now=T0 + 5)
    from gubernator_tpu.core.hashing import slot_hash_batch

    b = mesh_engine.snapshot_read(slot_hash_batch(keys), now=T0 + 5)
    # full-row compare: the r19 full-lane round-trip preserves duration
    assert a == b
    live = [r for r in a if r is not None]
    assert len(live) == 48  # leaky windows excluded by scope
    # decisions continue identically on the re-partitioned store
    hits = np.ones(len(keys), np.int64)
    kh = slot_hash_batch(keys)
    lim = np.full(len(keys), 5, np.int64)
    dur = np.full(len(keys), 60_000, np.int64)
    algo = np.zeros(len(keys), np.int32)
    gnp = np.zeros(len(keys), bool)
    token = [i for i in range(len(keys)) if i % 4 != 3]
    ra = flat.engine.decide_arrays(
        kh[token], hits[token], lim[token], dur[token], algo[token],
        gnp[token], T0 + 10,
    )
    rb = mesh_engine.decide_arrays(
        kh[token], hits[token], lim[token], dur[token], algo[token],
        gnp[token], T0 + 10,
    )
    for x, y in zip(ra, rb):
        np.testing.assert_array_equal(
            np.asarray(x, np.int64), np.asarray(y, np.int64)
        )


def test_mesh_backend_repartition_shard_count_change():
    """MeshBackend.repartition: 8 shards -> 2 shards -> flat, every
    live window preserved at each step (the GUBER_SHARDS change path);
    sticky-over windows keep answering OVER on a peek."""
    import jax

    be = MeshBackend(
        StoreConfig(rows=4, slots=256), devices=jax.devices(),
        buckets=(64,),
    )
    keys = _drive_windows(be)
    want = be.snapshot_read(keys, now=T0 + 5)
    assert be.engine.n == 8
    be.repartition(devices=jax.devices()[:2], now=T0 + 5)
    assert be.engine.n == 2
    assert be.snapshot_read(keys, now=T0 + 5) == want
    be.repartition(devices=jax.devices()[:1], now=T0 + 5)
    assert be.engine.flat
    assert be.snapshot_read(keys, now=T0 + 5) == want
    # over-limit state survived two re-partitions: a created-over
    # window (kind 2, sticky, remaining == limit) and an exhausted one
    # (kind 1, remaining == 0) both still peek OVER with their exact
    # remaining counts — no window re-opened anywhere in the chain
    sticky = [RateLimitReq(name="rp", unique_key=f"rp{i}", hits=0,
                           limit=5, duration=60_000)
              for i in range(64) if i % 4 == 2]
    exhausted = [RateLimitReq(name="rp", unique_key=f"rp{i}", hits=0,
                              limit=5, duration=60_000)
                 for i in range(64) if i % 4 == 1]
    for r in be.decide(sticky, [False] * len(sticky), now=T0 + 6):
        assert r.status == Status.OVER_LIMIT and r.remaining == 5
    for r in be.decide(exhausted, [False] * len(exhausted), now=T0 + 6):
        assert r.status == Status.OVER_LIMIT and r.remaining == 0


def test_export_windows_empty_and_scope():
    from gubernator_tpu.core.store import FLAG_ALGO_LEAKY

    flat = TpuBackend(StoreConfig(rows=4, slots=256), buckets=(64,))
    w = flat.engine.export_windows(now=T0)
    assert w["key_hash"].shape[0] == 0  # nothing ever decided
    _drive_windows(flat, n=8)
    w = flat.engine.export_windows(now=T0 + 5)
    # r19 widened the export to flag-aware rows: the 2 leaky entries
    # ride along now, carrying their algo bit in the flags lane
    assert w["key_hash"].shape[0] == 8
    assert int(
        ((w["flags"] & FLAG_ALGO_LEAKY) != 0).sum()
    ) == 2
    assert (w["duration"] == 60_000).all()
    # expired windows drop out of the export
    w = flat.engine.export_windows(now=T0 + 120_000)
    assert w["key_hash"].shape[0] == 0
