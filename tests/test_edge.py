"""Native serving edge e2e: C++ front-end -> unix-socket bridge -> daemon.

Skipped when the edge binary is not built (make -C
gubernator_tpu/native/edge). Asserts the edge parses gateway-style JSON
(string int64s, enum names), shares rate-limit state with the daemon's
own HTTP listener, and reports backend health.
"""

import json
import pathlib
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from tests._util import edge_binary

ROOT = pathlib.Path(__file__).resolve().parent.parent
EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

DAEMON_HTTP = 19184
EDGE_HTTP = 19185
GRPC = 19194
SOCK = "/tmp/guber-edge-pytest.sock"


def _post(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/GetRateLimits",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def edge_stack():
    import os

    try:
        os.unlink(SOCK)
    except FileNotFoundError:
        pass
    env = dict(
        os.environ,
        GUBER_BACKEND="exact",
        GUBER_GRPC_ADDRESS=f"127.0.0.1:{GRPC}",
        GUBER_HTTP_ADDRESS=f"127.0.0.1:{DAEMON_HTTP}",
        GUBER_EDGE_SOCKET=SOCK,
        PYTHONPATH=str(ROOT),
    )
    daemon = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=ROOT, env=env,
    )
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not pathlib.Path(SOCK).exists():
        time.sleep(0.2)
        if daemon.poll() is not None:
            pytest.fail(f"daemon died:\n{daemon.stdout.read()}")
    edge = subprocess.Popen(
        [str(EDGE_BIN), "--listen", str(EDGE_HTTP), "--backend", SOCK],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    time.sleep(0.3)
    yield
    edge.kill()
    daemon.terminate()
    daemon.wait(timeout=10)


def test_edge_serves_and_shares_state(edge_stack):
    out = _post(
        EDGE_HTTP,
        {
            "requests": [
                {"name": "e", "uniqueKey": "k1", "hits": 1, "limit": 3,
                 "duration": 60000},
                # gateway-style string int64s + enum name
                {"name": "e", "uniqueKey": "k2", "hits": "2", "limit": "5",
                 "duration": "60000", "algorithm": "LEAKY_BUCKET"},
            ]
        },
    )
    r = out["responses"]
    assert r[0]["status"] == "UNDER_LIMIT" and r[0]["remaining"] == "2"
    assert r[1]["status"] == "UNDER_LIMIT" and r[1]["remaining"] == "3"

    # state is shared with the daemon's own HTTP listener
    out2 = _post(
        DAEMON_HTTP,
        {"requests": [{"name": "e", "uniqueKey": "k1", "hits": 1,
                       "limit": 3, "duration": 60000}]},
    )
    assert out2["responses"][0]["remaining"] == "1"

    # and back through the edge again
    out3 = _post(
        EDGE_HTTP,
        {"requests": [{"name": "e", "uniqueKey": "k1", "hits": 1,
                       "limit": 3, "duration": 60000}]},
    )
    assert out3["responses"][0]["remaining"] == "0"


def test_edge_health_and_errors(edge_stack):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{EDGE_HTTP}/v1/HealthCheck", timeout=10
    ) as r:
        assert json.loads(r.read())["status"] == "healthy"

    req = urllib.request.Request(
        f"http://127.0.0.1:{EDGE_HTTP}/v1/GetRateLimits",
        data=b"{not json",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400

    # validation errors surface per-item through the frame protocol
    out = _post(
        EDGE_HTTP,
        {"requests": [{"name": "", "uniqueKey": "x", "hits": 1,
                       "limit": 1, "duration": 1000}]},
    )
    assert out["responses"][0]["error"] != ""


def test_edge_sigterm_graceful(edge_stack):
    """SIGTERM must drain and exit 0 — the daemon's graceful contract
    extends to the edge (reference main.go:127-139 drains on SIGINT)."""
    import signal as _signal
    import subprocess as _sp

    proc = _sp.Popen(
        [str(EDGE_BIN), "--listen", "19187", "--backend", SOCK],
        stdout=_sp.PIPE, stderr=_sp.STDOUT, text=True,
    )
    try:
        deadline = time.monotonic() + 10
        import socket as _socket

        while time.monotonic() < deadline:
            try:
                _socket.create_connection(
                    ("127.0.0.1", 19187), timeout=1
                ).close()
                break
            except OSError:
                time.sleep(0.05)
        # it serves...
        out = _post(19187, {"requests": [{"name": "g", "uniqueKey": "s",
                                         "hits": 1, "limit": 3,
                                         "duration": 60000}]})
        assert out["responses"][0]["status"] == "UNDER_LIMIT"
        # ...and drains cleanly on SIGTERM
        proc.send_signal(_signal.SIGTERM)
        assert proc.wait(timeout=15) == 0
        assert "draining" in proc.stdout.read()
    finally:
        # a failure above must not leak an edge bound to the port
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
