"""Compiled edge in front of a 3-node cluster (the r5 capability).

The reference's compiled binary IS a cluster node: its Go server
computes ring ownership and forwards (reference gubernator.go:114,
hash.go:80-96, peers.go:111-207). Here the compiled edge does the ring
math itself: it reads the membership from the bridge hello, computes
crc32 ownership per item in C++, builds one pre-hashed (GEB6) frame
per owner, and ships each frame to the owning node's TCP bridge.

These tests pin the three claims that make that sound:

- **placement parity**: the edge's crc32 ring (edge.cc crc32_ieee +
  Ring::owner) picks the SAME node as every daemon's picker
  (serve/peers.py / core/hashing.ring_hash). Checked exactly: each
  node's edge_fast_items_total must equal the Python-computed count of
  keys it owns — any divergence in the hash or the successor rule
  shifts at least one key to another node and breaks the equality.
- **exactly-once admission**: a key decided through the edge lives in
  ONE node's store; reading it back through a different node (whose
  instance forwards over gRPC to the ring owner) sees the consumed
  hits. A mis-routed decide would leave the true owner's bucket fresh.
- **owner metadata parity**: remote-owned items answered through the
  edge carry metadata.owner = the owner's gRPC address, like
  instance-side forwards (serve/instance.py forward()).

Daemons run the single-chip tpu backend on CPU like the other e2e
suites; the explicit GUBER_EDGE_PEER_BRIDGES map stands in for the
symmetric-port convention (all three nodes share 127.0.0.1 here).
"""

import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time
import urllib.request

import grpc
import pytest

from gubernator_tpu.api.grpc_glue import V1Stub
from gubernator_tpu.api.proto.gen import gubernator_pb2

from tests._util import edge_binary

ROOT = pathlib.Path(__file__).resolve().parent.parent
EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

N_NODES = 3
# Dynamic per-process ports (r8 deflake): this module also runs INSIDE
# tests/test_edge_asan.py as a subprocess suite — with the old fixed
# 1954x block, the inner and outer incarnations shared ports, and a
# lingering listener or the C++ edge's SO_REUSEADDR-less rebind over
# TIME_WAIT produced "address already in use" boot failures only under
# full-suite runs. Each process now allocates its own block.
from _util import free_ports as _free_ports  # noqa: E402


def _pick_ports():
    """Allocate the module's port block, re-rolling until the crc32
    ring induced by the gRPC addresses spreads the suite's key set
    over every node — the placement assertions below (exact per-node
    shares, 'every node serves some of 200 keys') assume a non-
    degenerate 3-point ring, which fixed addresses guaranteed by
    construction and random ports must re-establish."""
    from gubernator_tpu.core.hashing import ring_hash

    sample = [f"ec_ck-{i}" for i in range(200)]
    for _ in range(64):
        ports = _free_ports(3 * N_NODES + 2)
        addrs = [f"127.0.0.1:{p}" for p in ports[:N_NODES]]
        points = sorted((ring_hash(a), a) for a in addrs)
        ring = [p for p, _ in points]
        import bisect

        share = {a: 0 for a in addrs}
        for k in sample:
            i = bisect.bisect_left(ring, ring_hash(k))
            share[points[0 if i == len(ring) else i][1]] += 1
        if min(share.values()) >= 10:
            return ports
    raise RuntimeError("no balanced ring in 64 port rolls")


_PORTS = _pick_ports()
GRPC_PORTS = _PORTS[0:N_NODES]
HTTP_PORTS = _PORTS[N_NODES:2 * N_NODES]
BRIDGE_PORTS = _PORTS[2 * N_NODES:3 * N_NODES]
EDGE_HTTP = _PORTS[3 * N_NODES]
EDGE_GRPC = _PORTS[3 * N_NODES + 1]
SOCKS = [
    f"/tmp/guber-edge-cluster-{os.getpid()}-{i}.sock"
    for i in range(N_NODES)
]
GRPC_ADDRS = [f"127.0.0.1:{p}" for p in GRPC_PORTS]


def _spawn_cluster():
    peers = ",".join(GRPC_ADDRS)
    bridges = ",".join(
        f"{GRPC_ADDRS[i]}=127.0.0.1:{BRIDGE_PORTS[i]}"
        for i in range(N_NODES)
    )
    daemons = []
    for i in range(N_NODES):
        try:
            os.unlink(SOCKS[i])
        except FileNotFoundError:
            pass
        env = dict(
            os.environ,
            PYTHONPATH=str(ROOT),
            GUBER_BACKEND="tpu",
            GUBER_JAX_PLATFORM="cpu",
            GUBER_STORE_SLOTS=str(1 << 10),
            GUBER_GRPC_ADDRESS=GRPC_ADDRS[i],
            GUBER_HTTP_ADDRESS=f"127.0.0.1:{HTTP_PORTS[i]}",
            GUBER_ADVERTISE_ADDRESS=GRPC_ADDRS[i],
            GUBER_PEERS=peers,
            GUBER_EDGE_SOCKET=SOCKS[i],
            GUBER_EDGE_TCP=f"127.0.0.1:{BRIDGE_PORTS[i]}",
            GUBER_EDGE_PEER_BRIDGES=bridges,
            # teardown SIGTERMs the daemons, which drains (r8); the
            # cluster is idle by then so the drain is milliseconds —
            # a small budget just keeps the worst case snappy
            GUBER_DRAIN_TIMEOUT_MS="1000",
            JAX_COMPILATION_CACHE_DIR=str(ROOT / ".jax_cache_cpu"),
        )
        # log FILES, not an undrained stdout=PIPE: a daemon filling the
        # 64 KiB pipe buffer blocks mid-serve under full-suite load
        # (same deflake as test_compose_topology r8), and on failure
        # the log is readable without racing the pipe
        log_f = open(
            tempfile.mkstemp(prefix=f"guber-edge-cluster-{i}-",
                             suffix=".log")[0],
            "w+",
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
            stdout=log_f,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=ROOT,
            env=env,
        )
        proc._log = log_f  # noqa: SLF001 - test-local handle
        daemons.append(proc)

    def _dead(i, msg):
        for x in daemons:
            x.kill()
        d = daemons[i]
        d._log.flush()
        d._log.seek(0)
        pytest.fail(f"daemon {i} {msg}:\n{d._log.read()}")

    deadline = time.monotonic() + 240
    for i, d in enumerate(daemons):
        while not os.path.exists(SOCKS[i]):
            if d.poll() is not None:
                _dead(i, "died at boot")
            if time.monotonic() > deadline:
                for x in daemons:
                    x.kill()
                pytest.fail(f"daemon {i} never created its edge socket")
            time.sleep(0.2)
    # the edge socket appears before discovery settles; wait until every
    # node actually SERVES (health up, full peer count) so a test's
    # first HTTP call can never race a still-booting or just-crashed
    # node into an unexplained ConnectionRefused
    for i, d in enumerate(daemons):
        while True:
            if d.poll() is not None:
                _dead(i, "died before serving")
            try:
                h = json.loads(
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{HTTP_PORTS[i]}/v1/HealthCheck",
                        timeout=2,
                    ).read()
                )
                if h.get("peerCount") == N_NODES:
                    break
            except OSError:
                pass
            if time.monotonic() > deadline:
                _dead(i, "never became healthy")
            time.sleep(0.2)
    return daemons


@pytest.fixture(scope="module")
def cluster():
    daemons = _spawn_cluster()
    edge = subprocess.Popen(
        [
            str(EDGE_BIN),
            "--listen", str(EDGE_HTTP),
            "--grpc-listen", str(EDGE_GRPC),
            "--backend", SOCKS[0],
            "--ring-refresh-ms", "200",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.monotonic() + 10
    import socket as socketlib

    while True:
        if edge.poll() is not None:
            for d in daemons:
                d.kill()
            pytest.fail(f"edge died:\n{edge.stdout.read()}")
        try:
            socketlib.create_connection(
                ("127.0.0.1", EDGE_HTTP), timeout=1
            ).close()
            break
        except OSError:
            if time.monotonic() > deadline:
                edge.kill()
                for d in daemons:
                    d.kill()
                pytest.fail("edge never started listening")
            time.sleep(0.05)
    yield
    edge.kill()
    for d in daemons:
        d.terminate()
    for d in daemons:
        # never leak a daemon: a teardown that outlives the graceful
        # window is escalated to SIGKILL (a leaked process would hold
        # this module's fixed ports and poison later suites)
        try:
            d.wait(timeout=30)
        except subprocess.TimeoutExpired:
            d.kill()
            d.wait(timeout=10)


def _expected_owner(name: str, key: str) -> str:
    """The daemon-side ring answer (serve/peers.py picker semantics)."""
    import bisect

    from gubernator_tpu.core.hashing import ring_hash

    points = sorted((ring_hash(a), a) for a in GRPC_ADDRS)
    keys = [p for p, _ in points]
    i = bisect.bisect_left(keys, ring_hash(f"{name}_{key}"))
    if i == len(keys):
        i = 0
    return points[i][1]


def _metric(node: int, name: str) -> float:
    text = (
        urllib.request.urlopen(
            f"http://127.0.0.1:{HTTP_PORTS[node]}/metrics", timeout=10
        )
        .read()
        .decode()
    )
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return 0.0


# bounded-503-retry POSTs (r15 deflake): under full-suite load on one
# core a just-spawned lane or a starved bridge can refuse a frame with
# a transient 503; the refusal is un-served by contract, so the shared
# helper's bounded retry cannot double-charge (tests/_util.post_json)
def _daemon_http(node: int, body: dict) -> dict:
    from _util import post_json

    return post_json(
        f"http://127.0.0.1:{HTTP_PORTS[node]}/v1/GetRateLimits", body
    )


def _edge_http(body: dict) -> dict:
    from _util import post_json

    return post_json(
        f"http://127.0.0.1:{EDGE_HTTP}/v1/GetRateLimits", body
    )


def test_fast_frames_reach_every_owner_with_exact_placement(cluster):
    """200 distinct keys through the edge: every node must serve its
    exact Python-computed share of fast items, and every key must be
    admitted exactly once (readable via a DIFFERENT node's forward)."""
    keys = [f"ck-{i}" for i in range(200)]
    before = [_metric(i, "edge_fast_items_total") for i in range(N_NODES)]

    v1 = V1Stub(grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}"))
    # a few batches so co-batching happens; all fast-eligible
    for lo in range(0, len(keys), 50):
        r = v1.GetRateLimits(
            gubernator_pb2.GetRateLimitsReq(
                requests=[
                    gubernator_pb2.RateLimitReq(
                        name="ec", unique_key=k, hits=1, limit=9,
                        duration=60_000,
                    )
                    for k in keys[lo : lo + 50]
                ]
            )
        )
        assert all(x.remaining == 8 for x in r.responses), [
            (x.remaining, x.error) for x in r.responses if x.remaining != 8
        ]

    # placement parity, exact: per-node fast-item deltas == ownership
    # histogram computed with the daemon-side ring
    want = {a: 0 for a in GRPC_ADDRS}
    for k in keys:
        want[_expected_owner("ec", k)] += 1
    got = [
        _metric(i, "edge_fast_items_total") - before[i]
        for i in range(N_NODES)
    ]
    assert got == [float(want[a]) for a in GRPC_ADDRS], (got, want)
    # sanity: the spread touches every node (crc32 over 200 keys never
    # lands all on one point of a 3-node ring)
    assert all(g > 0 for g in got)

    # exactly-once: read back through each NODE directly (hits=0); the
    # instance forwards to the ring owner, which must hold the consumed
    # bucket. A mis-placed decide leaves the true owner fresh (9).
    for node in range(N_NODES):
        out = _daemon_http(
            node,
            {
                "requests": [
                    {"name": "ec", "uniqueKey": k, "hits": 0,
                     "limit": 9, "duration": 60000}
                    for k in keys[node::37]
                ]
            },
        )
        assert all(
            x["remaining"] == "8" for x in out["responses"]
        ), out["responses"]


def test_owner_metadata_on_remote_fast_items(cluster):
    """Edge responses carry metadata.owner for items owned by a node
    other than the edge's primary (parity with instance forwards)."""
    keys = [f"own-{i}" for i in range(40)]
    out = _edge_http(
        {
            "requests": [
                {"name": "ec", "uniqueKey": k, "hits": 1, "limit": 9,
                 "duration": 60000}
                for k in keys
            ]
        }
    )
    saw_remote = 0
    for k, resp in zip(keys, out["responses"]):
        owner = _expected_owner("ec", k)
        if owner == GRPC_ADDRS[0]:
            assert "owner" not in resp["metadata"], (k, resp)
        else:
            assert resp["metadata"].get("owner") == owner, (k, resp)
            saw_remote += 1
    assert saw_remote > 0


def test_global_items_still_ride_string_path_in_cluster(cluster):
    """GLOBAL behavior needs the instance's replica/gossip path: via
    the edge it must come back correct (decided under the owner's
    GLOBAL handling, not the pre-hashed local path)."""
    out = _edge_http(
        {
            "requests": [
                {"name": "ec", "uniqueKey": f"glob-{i}", "hits": 1,
                 "limit": 9, "duration": 60000, "behavior": "GLOBAL"}
                for i in range(12)
            ]
        }
    )
    assert all(
        x["status"] == "UNDER_LIMIT" and x["remaining"] == "8"
        for x in out["responses"]
    ), out["responses"]


def test_edge_health_in_cluster(cluster):
    body = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{EDGE_HTTP}/v1/HealthCheck", timeout=10
        ).read()
    )
    assert body["status"] == "healthy"
