"""Discovery pool tests with fake etcd3/kubernetes clients.

Round 1 shipped serve/discovery.py with zero executed lines (the client
libraries are absent in this image) — exactly the code that breaks in
production: lease-loss re-register, blocking-watch-on-worker-thread,
run_coroutine_threadsafe bridging, k8s stream handling. The pools accept
injected clients, so everything here runs against fakes (reference
behaviors: etcd.go:36-316, kubernetes.go:56-157).
"""

import asyncio
import sys
import threading
import types

import pytest

from gubernator_tpu.serve.discovery import EtcdPool, K8sPool, StaticPool


class FakeLease:
    def __init__(self, pool):
        self.pool = pool
        self.refreshes = 0

    def refresh(self):
        if self.pool.lease_dead:
            raise RuntimeError("lease expired")
        self.refreshes += 1


class FakeEtcd:
    """Minimal etcd3-compatible fake: kv store + prefix watch."""

    def __init__(self):
        self.kv = {}
        self.lease_dead = False
        self.leases = []
        self.registers = 0
        self._event = threading.Event()
        self._watch_cancelled = threading.Event()

    # -- client surface used by EtcdPool --------------------------------
    def lease(self, ttl):
        self.registers += 1
        lease = FakeLease(self)
        self.leases.append(lease)
        return lease

    def put(self, key, value, lease=None):
        self.kv[key] = value
        self._event.set()

    def delete(self, key):
        self.kv.pop(key, None)
        self._event.set()

    def get_prefix(self, prefix):
        return [
            (v.encode() if isinstance(v, str) else v, k)
            for k, v in sorted(self.kv.items())
            if k.startswith(prefix)
        ]

    def watch_prefix(self, prefix):
        def events():
            while not self._watch_cancelled.is_set():
                if self._event.wait(0.05):
                    self._event.clear()
                    yield object()  # event payload is unused

        return events(), self._watch_cancelled.set

    # -- test helpers ----------------------------------------------------
    def external_put(self, key, value):
        self.kv[key] = value
        self._event.set()


def run_pool_test(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=20))


def test_static_pool_marks_owner():
    seen = []

    async def on_update(peers):
        seen.append(peers)

    async def main():
        pool = StaticPool(["a:1", "b:2"], "b:2", on_update)
        await pool.start()
        await pool.close()

    run_pool_test(main())
    assert len(seen) == 1
    assert [(p.address, p.is_owner) for p in seen[0]] == [
        ("a:1", False), ("b:2", True),
    ]


def test_etcd_register_watch_and_close():
    fake = FakeEtcd()
    updates = []

    async def on_update(peers):
        updates.append(sorted((p.address, p.is_owner) for p in peers))

    async def main():
        pool = EtcdPool(
            ["etcd:2379"], "/guber/", "me:81", on_update, client=fake
        )
        await pool.start()
        # registration: own key under the prefix, bound to a lease
        assert fake.kv == {"/guber/me:81": "me:81"}
        assert fake.registers == 1
        # a peer joining fires the watch -> full peer snapshot pushed
        fake.external_put("/guber/peer:82", "peer:82")
        for _ in range(100):
            if len(updates) >= 2:
                break
            await asyncio.sleep(0.05)
        assert updates[-1] == [("me:81", True), ("peer:82", False)]
        await pool.close()
        # close deregisters (reference etcd.go: delete on shutdown)
        assert "/guber/me:81" not in fake.kv

    run_pool_test(main())
    assert updates[0] == [("me:81", True)]


def test_etcd_lease_loss_reregisters():
    fake = FakeEtcd()

    async def on_update(peers):
        pass

    async def main():
        pool = EtcdPool(
            ["etcd:2379"], "/guber/", "me:81", on_update, client=fake
        )
        pool.LEASE_TTL_S = 0.09  # fast keepalive cadence for the test
        await pool.start()
        assert fake.registers == 1
        fake.lease_dead = True  # refresh now raises -> re-register
        for _ in range(100):
            if fake.registers >= 2:
                break
            await asyncio.sleep(0.05)
        assert fake.registers >= 2, "lease loss did not re-register"
        await pool.close()

    run_pool_test(main())


def test_etcd_tls_kwargs_thread_into_client(monkeypatch, tmp_path):
    """GUBER_ETCD_TLS_* must reach etcd3.client as its TLS kwargs
    (reference cmd/gubernator/config.go:149-192 loads the bundle)."""
    captured = {}

    def fake_client(**kwargs):
        captured.update(kwargs)
        return FakeEtcd()

    fake_mod = types.ModuleType("etcd3")
    fake_mod.client = fake_client
    monkeypatch.setitem(sys.modules, "etcd3", fake_mod)

    async def on_update(peers):
        pass

    EtcdPool(
        ["etcd.internal:2379"], "/guber/", "me:81", on_update,
        tls_cert="/pki/cert.pem", tls_key="/pki/key.pem",
        tls_ca="/pki/ca.pem",
    )
    assert captured == {
        "host": "etcd.internal", "port": 2379,
        "ca_cert": "/pki/ca.pem", "cert_cert": "/pki/cert.pem",
        "cert_key": "/pki/key.pem",
    }

    with pytest.raises((ValueError, RuntimeError)):
        EtcdPool(
            ["etcd:2379"], "/guber/", "me:81", on_update,
            tls_cert="/pki/cert.pem",  # key missing
        )


def test_etcd_tls_config_env_parse():
    from gubernator_tpu.serve.config import config_from_env

    env = {
        "GUBER_GRPC_ADDRESS": "127.0.0.1:81",
        "GUBER_ETCD_ENDPOINTS": "etcd:2379",
        "GUBER_ETCD_TLS_CERT": "/pki/c.pem",
        "GUBER_ETCD_TLS_KEY": "/pki/k.pem",
        "GUBER_ETCD_TLS_CA": "/pki/ca.pem",
    }
    conf = config_from_env(env)
    assert conf.etcd_tls_cert == "/pki/c.pem"
    assert conf.etcd_tls_key == "/pki/k.pem"
    assert conf.etcd_tls_ca == "/pki/ca.pem"

    env["GUBER_ETCD_TLS_KEY"] = ""
    with pytest.raises(ValueError):
        config_from_env(env)


class FakeEndpoints:
    def __init__(self, ips):
        addr = [types.SimpleNamespace(ip=ip) for ip in ips]
        self.subsets = [types.SimpleNamespace(addresses=addr)]


class FakeK8sWatch:
    def __init__(self, batches):
        self.batches = batches
        self.stopped = threading.Event()

    def stream(self, fn, namespace, label_selector):
        for ips in self.batches:
            yield {"object": FakeEndpoints(ips)}
        # keep the stream open like a real watch, but stoppable so the
        # test's executor threads can shut down
        self.stopped.wait(timeout=30)


def test_k8s_pool_pushes_endpoints_and_marks_self():
    updates = []

    async def on_update(peers):
        updates.append(sorted((p.address, p.is_owner) for p in peers))

    watch = FakeK8sWatch([["10.0.0.1"], ["10.0.0.1", "10.0.0.2"]])

    async def main():
        pool = K8sPool(
            namespace="default",
            selector="app=guber",
            pod_ip="10.0.0.2",
            pod_port="81",
            on_update=on_update,
            api=types.SimpleNamespace(
                list_namespaced_endpoints=lambda *a, **k: None
            ),
            watch=watch,
        )
        await pool.start()
        for _ in range(100):
            if len(updates) >= 2:
                break
            await asyncio.sleep(0.05)
        await pool.close()
        watch.stopped.set()  # release the blocked stream thread

    run_pool_test(main())
    assert updates[0] == [("10.0.0.1:81", False)]
    assert updates[1] == [("10.0.0.1:81", False), ("10.0.0.2:81", True)]


def test_fakes_match_discovery_contract():
    """Both-direction drift guard (r2 verdict #5): the fakes must accept
    exactly the call shapes production makes — the same shapes
    tests/test_discovery_real.py pins on the REAL etcd3/kubernetes
    libraries when they are installed. A fake that grows out of sync
    with the contract fails here; a library that moves fails there."""
    from _discovery_contract import (
        ETCD_CLIENT_CALLS,
        ETCD_LEASE_CALLS,
        K8S_WATCH_CALLS,
        assert_object_implements,
    )

    fake = FakeEtcd()
    assert_object_implements(fake, ETCD_CLIENT_CALLS, "FakeEtcd")
    assert_object_implements(fake.lease(30), ETCD_LEASE_CALLS, "FakeLease")
    watch = FakeK8sWatch([])
    assert_object_implements(
        watch, {"stream": K8S_WATCH_CALLS["stream"]}, "FakeK8sWatch"
    )
    # FakeK8sWatch models stop via its stopped event (K8sPool.close calls
    # watch.stop when present; the fake documents the divergence by
    # construction) and the watch_prefix fake must return the
    # (iterator, cancel) pair shape
    events, cancel = fake.watch_prefix("/p/")
    assert callable(cancel) and hasattr(events, "__iter__")
