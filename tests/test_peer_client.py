"""PeerClient unit tests: the micro-batch flusher's load-bearing
behaviors (reference peers.go:143-207) driven against a fake stub —
flush at batch_limit without waiting, flush at the batch_wait window,
whole-batch failure fan-back, response-count-mismatch rejection, and
close() failing (not stranding) queued callers.
"""

import asyncio

import pytest

from gubernator_tpu.api import convert
from gubernator_tpu.api.proto.gen import peers_pb2
from gubernator_tpu.api.types import Behavior, RateLimitReq, RateLimitResp
from gubernator_tpu.serve.config import BehaviorConfig
from gubernator_tpu.serve.peers import PeerClient


def _req(i: int) -> RateLimitReq:
    return RateLimitReq(
        name="pc", unique_key=f"k{i}", hits=1, limit=10, duration=1000,
        behavior=Behavior.BATCHING,
    )


class FakeStub:
    """Records each GetPeerRateLimits batch; echoes per-request answers."""

    def __init__(self):
        self.batches = []
        self.fail_next = None
        self.short_response = False
        self.release = asyncio.Event()
        self.release.set()

    async def GetPeerRateLimits(self, pb_req, timeout=None):
        # record on ENTRY so tests can wait for "flusher inside the RPC"
        self.batches.append([r.unique_key for r in pb_req.requests])
        await self.release.wait()
        if self.fail_next:
            e, self.fail_next = self.fail_next, None
            raise e
        n = len(pb_req.requests)
        if self.short_response:
            n -= 1
        return peers_pb2.GetPeerRateLimitsResp(
            rate_limits=[
                convert.resp_to_pb(RateLimitResp(limit=10, remaining=7))
                for _ in range(n)
            ]
        )


def _client(stub, **conf_kwargs) -> PeerClient:
    conf = BehaviorConfig(**conf_kwargs)
    c = PeerClient(conf, "127.0.0.1:1")
    c.stub = stub
    c._flusher = asyncio.ensure_future(c._run())
    return c


def test_flush_at_batch_limit_without_waiting():
    async def scenario():
        stub = FakeStub()
        # a long window that must NOT be waited out once limit hits
        c = _client(stub, batch_wait=5.0, batch_limit=3)
        stub.release.clear()  # hold the RPC so the queue accumulates
        futs = [
            asyncio.ensure_future(c.get_peer_rate_limit(_req(i)))
            for i in range(3)
        ]
        await asyncio.sleep(0.05)
        stub.release.set()
        resps = await asyncio.wait_for(asyncio.gather(*futs), timeout=2)
        assert [r.remaining for r in resps] == [7, 7, 7]
        assert stub.batches == [["k0", "k1", "k2"]]  # one coalesced RPC
        await c.close()

    asyncio.run(scenario())


def test_flush_at_window_for_partial_batch():
    async def scenario():
        stub = FakeStub()
        c = _client(stub, batch_wait=0.02, batch_limit=100)
        r = await asyncio.wait_for(
            c.get_peer_rate_limit(_req(0)), timeout=2
        )
        assert r.remaining == 7
        assert stub.batches == [["k0"]]
        await c.close()

    asyncio.run(scenario())


def test_batch_failure_fans_back_to_every_caller():
    async def scenario():
        stub = FakeStub()
        stub.release.clear()
        stub.fail_next = RuntimeError("owner exploded")
        c = _client(stub, batch_wait=0.005, batch_limit=10)
        futs = [
            asyncio.ensure_future(c.get_peer_rate_limit(_req(i)))
            for i in range(4)
        ]
        await asyncio.sleep(0.02)
        stub.release.set()
        for f in futs:
            with pytest.raises(RuntimeError, match="owner exploded"):
                await asyncio.wait_for(f, timeout=2)
        # the flusher survives a failed batch
        r = await asyncio.wait_for(
            c.get_peer_rate_limit(_req(9)), timeout=2
        )
        assert r.remaining == 7
        await c.close()

    asyncio.run(scenario())


def test_response_count_mismatch_rejected():
    async def scenario():
        stub = FakeStub()
        stub.short_response = True
        c = _client(stub, batch_wait=0, batch_limit=10)
        with pytest.raises(RuntimeError, match="mismatched"):
            await asyncio.wait_for(
                c.get_peer_rate_limit(_req(0)), timeout=2
            )
        await c.close()

    asyncio.run(scenario())


def test_enqueue_after_close_fails_fast():
    async def scenario():
        stub = FakeStub()
        c = _client(stub, batch_wait=0, batch_limit=10)
        await c.close()
        # a caller holding this peer object across set_peers must get an
        # immediate error, not enqueue into a queue nothing reads
        with pytest.raises(RuntimeError, match="is closed"):
            await asyncio.wait_for(c.get_peer_rate_limit(_req(0)), 2)

    asyncio.run(scenario())


def test_close_fails_queued_callers_instead_of_stranding():
    async def scenario():
        stub = FakeStub()
        stub.release.clear()  # first RPC parks the flusher mid-send
        c = _client(stub, batch_wait=0, batch_limit=1)
        f1 = asyncio.ensure_future(c.get_peer_rate_limit(_req(0)))
        while not stub.batches:  # flusher is now inside the held RPC
            await asyncio.sleep(0.001)
        f2 = asyncio.ensure_future(c.get_peer_rate_limit(_req(1)))
        await asyncio.sleep(0.01)
        await c.close()
        with pytest.raises(RuntimeError, match="closed mid-batch"):
            await asyncio.wait_for(f1, timeout=2)
        with pytest.raises(RuntimeError, match="closed mid-batch"):
            await asyncio.wait_for(f2, timeout=2)

    asyncio.run(scenario())
