"""Multi-host mesh e2e: two real OS processes, one global jax mesh.

Spawns a leader and a follower (tests/_multihost_runner.py), each with
one CPU device, joined via jax.distributed; the leader drives decide /
sync_globals / update_globals batches whose psum collectives cross the
process boundary (gloo over TCP — the CPU stand-in for DCN), with the
lockstep step pipe keeping both controllers issuing identical programs.
"""

import os
import socket
import subprocess
import sys
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pair(leader_role: str, follower_role: str, leader_timeout: float):
    """Spawn a (leader, follower) runner pair and return their outputs.

    No pytest-timeout in this image (the mark would be inert); the
    communicate(timeout=...) calls are the real watchdog — on expiry both
    processes are killed and the test fails with both logs."""
    coord = f"127.0.0.1:{_free_port()}"
    step_port = str(_free_port())
    runner = str(ROOT / "tests" / "_multihost_runner.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one device per process, no forced count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(ROOT) + os.pathsep + env.get("PYTHONPATH", "")

    follower = subprocess.Popen(
        [sys.executable, runner, follower_role, coord, step_port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=ROOT, env=env,
    )
    leader = subprocess.Popen(
        [sys.executable, runner, leader_role, coord, step_port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=ROOT, env=env,
    )
    try:
        l_out, _ = leader.communicate(timeout=leader_timeout)
        f_out, _ = follower.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        leader.kill()
        follower.kill()
        l_out = leader.communicate()[0]
        f_out = follower.communicate()[0]
        pytest.fail(f"timeout\nleader:\n{l_out}\nfollower:\n{f_out}")
    return leader.returncode, l_out, follower.returncode, f_out


def test_two_process_mesh():
    l_rc, l_out, f_rc, f_out = _run_pair("leader", "follower", 150)
    assert l_rc == 0 and "LEADER-OK" in l_out, (
        f"leader failed:\n{l_out}\nfollower:\n{f_out}"
    )
    assert f_rc == 0 and "FOLLOWER-OK" in f_out, (
        f"follower failed:\n{f_out}"
    )


def test_config_mismatch_fails_loudly_at_connect():
    """A follower constructed with a different bucket ladder must be
    rejected by the connect-time handshake on BOTH sides with the
    mismatch diagnostic — not hang or diverge later in lockstep."""
    l_rc, l_out, f_rc, f_out = _run_pair(
        "leader-mismatch", "follower-mismatch", 60
    )
    assert l_rc == 0 and "LEADER-MISMATCH-OK" in l_out, (
        f"leader:\n{l_out}\nfollower:\n{f_out}"
    )
    assert f_rc == 0 and "FOLLOWER-MISMATCH-OK" in f_out, (
        f"follower:\n{f_out}"
    )
