"""Multi-host mesh e2e: two real OS processes, one global jax mesh.

Spawns a leader and a follower (tests/_multihost_runner.py), each with
one CPU device, joined via jax.distributed; the leader drives decide /
sync_globals / update_globals batches whose psum collectives cross the
process boundary (gloo over TCP — the CPU stand-in for DCN), with the
lockstep step pipe keeping both controllers issuing identical programs.
"""

import os
import socket
import subprocess
import sys
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh():
    # no pytest-timeout in this image (the mark would be inert); the
    # subprocess communicate(timeout=...) calls below are the real
    # watchdog — worst case ~180s, then kill + fail with both logs
    coord = f"127.0.0.1:{_free_port()}"
    step_port = str(_free_port())
    runner = str(ROOT / "tests" / "_multihost_runner.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one device per process, no forced count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(ROOT) + os.pathsep + env.get("PYTHONPATH", "")

    follower = subprocess.Popen(
        [sys.executable, runner, "follower", coord, step_port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=ROOT, env=env,
    )
    leader = subprocess.Popen(
        [sys.executable, runner, "leader", coord, step_port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=ROOT, env=env,
    )
    try:
        l_out, _ = leader.communicate(timeout=150)
        f_out, _ = follower.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        leader.kill()
        follower.kill()
        l_out = leader.communicate()[0]
        f_out = follower.communicate()[0]
        pytest.fail(f"timeout\nleader:\n{l_out}\nfollower:\n{f_out}")

    assert leader.returncode == 0 and "LEADER-OK" in l_out, (
        f"leader failed:\n{l_out}\nfollower:\n{f_out}"
    )
    assert follower.returncode == 0 and "FOLLOWER-OK" in f_out, (
        f"follower failed:\n{f_out}"
    )
