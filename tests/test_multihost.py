"""Multi-host mesh e2e: several real OS processes, one global jax mesh.

Spawns a leader plus followers (tests/_multihost_runner.py), each process
holding one or more CPU devices, joined via jax.distributed; the leader
drives decide / sync_globals / update_globals batches whose psum
collectives cross the process boundary (gloo over TCP — the CPU stand-in
for DCN), with the lockstep step pipe keeping every controller issuing
identical programs. Topologies beyond 2x1 exercise the v5e-32 shape:
multiple devices per process with the process-major mesh ordering the
scaling model relies on, asserted inside every runner process.
"""

import os
import re
import socket
import subprocess
import sys
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_group(
    nprocs: int,
    devs_per_proc: int,
    leader_timeout: float,
    leader_role: str = "leader",
    follower_role: str = "follower",
):
    """Spawn a leader + (nprocs-1) followers; return everyone's output.

    No pytest-timeout in this image (the mark would be inert); the
    communicate(timeout=...) calls are the real watchdog — on expiry all
    processes are killed and the test fails with every log."""
    coord = f"127.0.0.1:{_free_port()}"
    step_ports = [str(_free_port()) for _ in range(nprocs - 1)]
    runner = str(ROOT / "tests" / "_multihost_runner.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    if devs_per_proc > 1:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devs_per_proc}"
        )
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(ROOT) + os.pathsep + env.get("PYTHONPATH", "")

    followers = [
        subprocess.Popen(
            [sys.executable, runner, follower_role, coord, port,
             str(fpid + 1), str(nprocs)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=ROOT, env=env,
        )
        for fpid, port in enumerate(step_ports)
    ]
    leader = subprocess.Popen(
        [sys.executable, runner, leader_role, coord, ",".join(step_ports),
         "0", str(nprocs)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=ROOT, env=env,
    )
    try:
        l_out, _ = leader.communicate(timeout=leader_timeout)
        f_outs = [f.communicate(timeout=30)[0] for f in followers]
    except subprocess.TimeoutExpired:
        leader.kill()
        for f in followers:
            f.kill()
        l_out = leader.communicate()[0]
        f_outs = [f.communicate()[0] for f in followers]
        pytest.fail(
            "timeout\nleader:\n%s\nfollowers:\n%s" % (l_out, "\n".join(f_outs))
        )
    return (
        leader.returncode, l_out,
        [f.returncode for f in followers], f_outs,
    )


def _assert_ok(l_rc, l_out, f_rcs, f_outs):
    assert l_rc == 0 and "LEADER-OK" in l_out, (
        f"leader failed:\n{l_out}\nfollowers:\n" + "\n".join(f_outs)
    )
    # r20 legs ran inside the leader: mesh-native GLOBAL hits collective
    # and the multihost sketch tier (lockstep promote) both differential
    # against a flat reference engine; r21 adds the window-ring leg
    # (sliding + GCRA served from the sketch, bit-exact vs host twins)
    assert "GHITS-OK" in l_out and "SKETCH-OK" in l_out, l_out
    assert "RING-OK" in l_out, l_out
    for rc, out in zip(f_rcs, f_outs):
        assert rc == 0 and "FOLLOWER-OK" in out, f"follower failed:\n{out}"


def _work(l_out: str) -> int:
    m = re.search(r"TOPO shards=(\d+) b_sub=(\d+)", l_out)
    assert m, l_out
    return int(m.group(1)) * int(m.group(2))


# each topology spawns nprocs jax processes on one core — run each ONCE
# and share the outputs between its own test and the flatness check
_RESULTS = {}


def _group(nprocs: int, devs: int, timeout: float = 300):
    key = (nprocs, devs)
    if key not in _RESULTS:
        _RESULTS[key] = _run_group(nprocs, devs, timeout)
    return _RESULTS[key]


def test_two_process_mesh():
    l_rc, l_out, f_rcs, f_outs = _group(2, 1, 150)
    _assert_ok(l_rc, l_out, f_rcs, f_outs)


def test_two_procs_four_devices_each():
    """2 hosts x 4 chips: the multi-device-per-process form of the
    v5e-32 story — 8 global shards, process-major ordering asserted in
    both processes, batch rows spread across all 8."""
    l_rc, l_out, f_rcs, f_outs = _group(2, 4)
    _assert_ok(l_rc, l_out, f_rcs, f_outs)


def test_four_procs_two_devices_each():
    """4 hosts x 2 chips: more processes than the lockstep pipe has ever
    seen — 3 followers acking every step, 8 global shards."""
    l_rc, l_out, f_rcs, f_outs = _group(4, 2)
    _assert_ok(l_rc, l_out, f_rcs, f_outs)


def test_cross_topology_work_flatness():
    """Mesh-scaling-style check across process topologies: per-row padded
    work (n_shards * B_sub / real rows) for the same rows-per-shard load
    must stay within 2x across 2x1, 2x4, and 4x2 — sharding across more
    processes/devices must not inflate total padded rows superlinearly."""
    results = {}
    for nprocs, devs in ((2, 1), (2, 4), (4, 2)):
        l_rc, l_out, f_rcs, f_outs = _group(nprocs, devs)
        _assert_ok(l_rc, l_out, f_rcs, f_outs)
        shards = nprocs * devs
        results[(nprocs, devs)] = _work(l_out) / (16 * shards)
    worst = max(results.values()) / min(results.values())
    assert worst <= 2.0, results


def test_config_mismatch_fails_loudly_at_connect():
    """A follower constructed with a different bucket ladder must be
    rejected by the connect-time handshake on BOTH sides with the
    mismatch diagnostic — not hang or diverge later in lockstep."""
    l_rc, l_out, f_rcs, f_outs = _run_group(
        2, 1, 60,
        leader_role="leader-mismatch", follower_role="follower-mismatch",
    )
    assert l_rc == 0 and "LEADER-MISMATCH-OK" in l_out, (
        f"leader:\n{l_out}\nfollowers:\n" + "\n".join(f_outs)
    )
    for rc, out in zip(f_rcs, f_outs):
        assert rc == 0 and "FOLLOWER-MISMATCH-OK" in out, (
            f"follower:\n{out}"
        )
