"""Traffic sketches: HLL accuracy envelope + Space-Saving guarantees."""

import numpy as np
import pytest

from gubernator_tpu.core.hashing import slot_hash_batch
from gubernator_tpu.core.sketches import (
    HyperLogLog,
    SpaceSaving,
    TrafficStats,
)


def _hashes(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**64, n, dtype=np.uint64)


@pytest.mark.parametrize("n", [100, 10_000, 200_000])
def test_hll_estimate_within_envelope(n):
    h = HyperLogLog(p=14)
    h.add_hashes(_hashes(n))
    est = h.estimate()
    # 1.04/sqrt(2^14) ~ 0.8% typical error; allow 5 sigma
    assert abs(est - n) <= max(0.05 * n, 10), (est, n)


def test_hll_duplicates_do_not_inflate():
    h = HyperLogLog(p=14)
    hashes = _hashes(1000)
    for _ in range(50):
        h.add_hashes(hashes)
    assert abs(h.estimate() - 1000) <= 60


def test_hll_merge_matches_union():
    a, b = HyperLogLog(p=12), HyperLogLog(p=12)
    ha, hb = _hashes(5000, seed=1), _hashes(5000, seed=2)
    a.add_hashes(ha)
    b.add_hashes(hb)
    a.merge(b)
    u = HyperLogLog(p=12)
    u.add_hashes(np.concatenate([ha, hb]))
    assert a.estimate() == u.estimate()


def test_hll_real_key_hashes():
    h = HyperLogLog(p=14)
    keys = [f"svc_{i}:acct_{i % 997}" for i in range(30_000)]
    h.add_hashes(slot_hash_batch(keys))
    distinct = len(set(keys))
    assert abs(h.estimate() - distinct) <= 0.05 * distinct


def test_space_saving_finds_heavy_hitters():
    rng = np.random.default_rng(3)
    # zipf stream over 10k keys: the top keys dominate
    stream = [f"key_{z}" for z in rng.zipf(1.3, 50_000) % 10_000]
    ss = SpaceSaving(capacity=128)
    for i in range(0, len(stream), 500):
        ss.observe(stream[i : i + 500])

    true_counts = {}
    for k in stream:
        true_counts[k] = true_counts.get(k, 0) + 1
    true_top = sorted(true_counts, key=true_counts.get, reverse=True)[:5]

    reported = [k for k, _, _ in ss.top(20)]
    for k in true_top:
        assert k in reported, f"missed heavy hitter {k}"
    # count-err is a valid lower bound; count an upper-ish estimate
    for k, c, e in ss.top(20):
        if k in true_counts:
            assert c - e <= true_counts[k] <= c, (k, c, e, true_counts[k])


def test_space_saving_capacity_bound():
    ss = SpaceSaving(capacity=16)
    ss.observe([f"k{i}" for i in range(1000)])
    assert len(ss.top(100)) <= 16
    assert ss.total == 1000


def test_traffic_stats_snapshot():
    ts = TrafficStats()
    keys = ["a_1", "a_1", "b_2"]
    ts.observe(keys, slot_hash_batch(keys))
    snap = ts.snapshot()
    assert snap["observed_total"] == 3
    assert snap["hot_keys"][0]["key"] == "a_1"
    assert snap["hot_keys"][0]["count"] == 2
    assert 1 <= snap["distinct_keys_estimate"] <= 3
