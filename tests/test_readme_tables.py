"""README perf tables must trace to committed artifacts — as a TEST.

`scripts/gen_readme_tables.py --check` regenerates every sentinel block
from the committed BENCH_* JSON artifacts and fails on any drift. It ran
only by convention before (r5 landed it, nothing enforced it); running
it as a tier-1 test means a PR that edits a perf number by hand, or
commits a new artifact without regenerating, fails loudly here instead
of publishing tables that say something the artifacts don't.
"""

import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_readme_tables_match_artifacts():
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "gen_readme_tables.py"),
         "--check"],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=ROOT,
    )
    assert r.returncode == 0, (
        f"README tables drifted from the committed artifacts "
        f"(rc={r.returncode}). Regenerate with `make readme`.\n"
        f"{r.stderr}"
    )
