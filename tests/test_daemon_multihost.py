"""Daemon-level multihost coverage (previously untested: the engine-level
lockstep suite never exercised cli/daemon.py's GUBER_DIST_* wiring).

- Fail-fast validation: the misconfigurations that would otherwise
  deadlock a whole mesh inside a collective (leader without
  backend=multihost, follower count mismatch, follower without a step
  listener) must exit with a diagnostic BEFORE joining jax.distributed.
- Full 2-daemon e2e: a leader daemon serving real gRPC over a 2-process
  global mesh with a follower daemon in lockstep — rate-limit
  transitions, health, graceful leader SIGTERM whose pipe close must
  release the follower (both exit 0).
"""

import os
import pathlib
import signal
import subprocess
import sys
import time

import grpc
import pytest

from _util import free_ports

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _clean_env(**extra) -> dict:
    """Ambient env minus any stray GUBER_* vars (a developer shell's
    GUBER_DIST_STEP_LISTEN would defeat the fail-fast assertions)."""
    env = {k: v for k, v in os.environ.items() if not k.startswith("GUBER_")}
    env["PYTHONPATH"] = str(ROOT)
    env.update(extra)
    return env


def _run_daemon_env(env_lines, timeout=30):
    """Run the daemon with a config file; return (rc, output)."""
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".conf", delete=False) as f:
        f.write("\n".join(env_lines) + "\n")
        path = f.name
    try:
        env = _clean_env()
        out = subprocess.run(
            [sys.executable, "-m", "gubernator_tpu.cli.daemon",
             "--config", path],
            capture_output=True, text=True, timeout=timeout, cwd=ROOT,
            env=env,
        )
        return out.returncode, out.stdout + out.stderr
    finally:
        os.unlink(path)


def test_leader_requires_multihost_backend():
    rc, out = _run_daemon_env([
        "GUBER_GRPC_ADDRESS=127.0.0.1:0",
        "GUBER_BACKEND=exact",
        "GUBER_DIST_COORDINATOR=127.0.0.1:1",
        "GUBER_DIST_NUM_PROCESSES=2",
        "GUBER_DIST_PROCESS_ID=0",
        "GUBER_DIST_FOLLOWERS=127.0.0.1:2",
    ])
    assert rc != 0
    assert "GUBER_BACKEND=multihost" in out, out[-500:]


def test_leader_follower_count_must_match():
    rc, out = _run_daemon_env([
        "GUBER_GRPC_ADDRESS=127.0.0.1:0",
        "GUBER_BACKEND=multihost",
        "GUBER_DIST_COORDINATOR=127.0.0.1:1",
        "GUBER_DIST_NUM_PROCESSES=3",
        "GUBER_DIST_PROCESS_ID=0",
        "GUBER_DIST_FOLLOWERS=127.0.0.1:2",
    ])
    assert rc != 0
    assert "implies" in out and "2 followers" in out, out[-500:]


def test_follower_requires_step_listen():
    rc, out = _run_daemon_env([
        "GUBER_GRPC_ADDRESS=127.0.0.1:0",
        "GUBER_DIST_COORDINATOR=127.0.0.1:1",
        "GUBER_DIST_NUM_PROCESSES=2",
        "GUBER_DIST_PROCESS_ID=1",
    ])
    assert rc != 0
    assert "GUBER_DIST_STEP_LISTEN" in out, out[-500:]


@pytest.mark.slow
def test_two_daemon_multihost_e2e():
    """Leader daemon + follower daemon as REAL processes: gRPC serving
    over a 2-process jax.distributed mesh with the lockstep pipe, the
    smallest ladder the serving tier's cross-validation allows
    (GUBER_DEVICE_BATCH_LIMIT=1024 >= the 1000-item per-RPC cap).
    Asserts decisions, health, and graceful SIGTERM shutdown.

    Marked slow (the chaos-soak convention): the lockstep warmup
    compiles the whole sub-rung ladder through 2-process gloo
    collectives, ~8-10 minutes on a 2-core box — the ENGINE-level
    multihost suite (tests/test_multihost.py) covers the global-mesh
    collectives in tier-1."""
    coord_port, step_port, grpc_port = free_ports(3)
    base = _clean_env(
        GUBER_JAX_PLATFORM="cpu",
        GUBER_DIST_COORDINATOR=f"127.0.0.1:{coord_port}",
        GUBER_DIST_NUM_PROCESSES="2",
        GUBER_DEVICE_BATCH_LIMIT="1024",
        GUBER_STORE_SLOTS="256",
    )
    # daemon logs go to files, not pipes: an undrained pipe filling its
    # ~64KB buffer would block the daemon mid-warmup and masquerade as a
    # startup timeout
    import tempfile

    l_log = tempfile.NamedTemporaryFile(
        "w+", suffix=".leader.log", delete=False
    )
    f_log = tempfile.NamedTemporaryFile(
        "w+", suffix=".follower.log", delete=False
    )
    follower = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        env=dict(
            base,
            GUBER_DIST_PROCESS_ID="1",
            GUBER_DIST_STEP_LISTEN=f"127.0.0.1:{step_port}",
        ),
        stdout=f_log, stderr=subprocess.STDOUT, cwd=ROOT,
    )
    leader = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        env=dict(
            base,
            GUBER_BACKEND="multihost",
            GUBER_DIST_PROCESS_ID="0",
            GUBER_DIST_FOLLOWERS=f"127.0.0.1:{step_port}",
            GUBER_GRPC_ADDRESS=f"127.0.0.1:{grpc_port}",
            GUBER_PEERS=f"127.0.0.1:{grpc_port}",
            GUBER_ADVERTISE_ADDRESS=f"127.0.0.1:{grpc_port}",
        ),
        stdout=l_log, stderr=subprocess.STDOUT, cwd=ROOT,
    )

    def _logs():
        l_log.flush()
        f_log.flush()
        return (
            pathlib.Path(l_log.name).read_text()[-2000:],
            pathlib.Path(f_log.name).read_text()[-2000:],
        )

    def _fail(msg):
        leader.kill()
        follower.kill()
        leader.wait(timeout=10)
        follower.wait(timeout=10)
        l_out, f_out = _logs()
        pytest.fail(f"{msg}\nleader:\n{l_out}\nfollower:\n{f_out}")

    try:
        from gubernator_tpu.api.grpc_glue import V1Stub
        from gubernator_tpu.api.proto.gen import gubernator_pb2

        chan = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
        stub = V1Stub(chan)
        deadline = time.monotonic() + 900  # lockstep warmup compiles
        # the sub-rung ladder over 2-process gloo (minutes on CPU)
        hc = None
        while time.monotonic() < deadline:
            if leader.poll() is not None or follower.poll() is not None:
                _fail("a daemon died during startup")
            try:
                hc = stub.HealthCheck(
                    gubernator_pb2.HealthCheckReq(), timeout=2
                )
                break
            except grpc.RpcError:
                time.sleep(1.0)
        if hc is None:
            _fail("leader gRPC never became healthy")
        assert hc.status == "healthy", hc

        r = gubernator_pb2.RateLimitReq(
            name="mh-daemon", unique_key="k", hits=1, limit=2,
            duration=60_000,
        )
        seq = []
        for _ in range(3):
            resp = stub.GetRateLimits(
                gubernator_pb2.GetRateLimitsReq(requests=[r]), timeout=30
            ).responses[0]
            seq.append((resp.status, resp.remaining))
        assert seq == [(0, 1), (0, 0), (1, 0)], seq

        # graceful shutdown: SIGTERM the leader; its pipe close must end
        # the follower_loop on its own (that release IS what this
        # asserts — a lingering follower is the regression)
        leader.send_signal(signal.SIGTERM)
        l_rc = leader.wait(timeout=60)
        try:
            f_rc = follower.wait(timeout=30)
        except subprocess.TimeoutExpired:
            _fail("follower not released by the leader's pipe close")
        assert l_rc == 0, (l_rc, _logs()[0])
        assert f_rc == 0, (f_rc, _logs()[1])
    finally:
        for p in (leader, follower):
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        os.unlink(l_log.name)
        os.unlink(f_log.name)
