"""HTTP JSON gateway tests: the grpc-gateway-compatible REST surface.

The reference exposes `POST /v1/GetRateLimits` and `GET /v1/HealthCheck`
through grpc-gateway (reference gubernator.pb.gw.go) plus `/metrics`;
this drives the aiohttp twin (serve/server.py) over real sockets —
field-name conversion (camelCase), string-encoded int64s, per-item
errors, malformed-body handling, and the observability routes.
"""

import json
import urllib.error
import urllib.request

import pytest

from _util import free_ports
from gubernator_tpu.cluster import LocalCluster


@pytest.fixture(scope="module")
def http_node():
    (g, h) = free_ports(2)
    c = LocalCluster(
        [f"127.0.0.1:{g}"], http_addresses=[f"127.0.0.1:{h}"]
    )
    c.start()
    yield f"http://127.0.0.1:{h}"
    c.stop()


def _post(base, path, body, timeout=10):
    req = urllib.request.Request(
        base + path,
        body if isinstance(body, bytes) else json.dumps(body).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_get_rate_limits_json_round_trip(http_node):
    body = {
        "requests": [
            {
                "name": "gw",
                "uniqueKey": "account:7",  # camelCase, as grpc-gateway
                "hits": "1",  # string int64, as grpc-gateway emits
                "limit": 2,
                "duration": 60000,
                "algorithm": "TOKEN_BUCKET",
            }
        ]
    }
    r1 = _post(http_node, "/v1/GetRateLimits", body)["responses"][0]
    assert r1["status"] == "UNDER_LIMIT"
    assert r1["remaining"] == "1"  # int64s come back as strings
    assert r1["limit"] == "2"
    r2 = _post(http_node, "/v1/GetRateLimits", body)["responses"][0]
    r3 = _post(http_node, "/v1/GetRateLimits", body)["responses"][0]
    assert (r2["remaining"], r3["status"]) == ("0", "OVER_LIMIT")


def test_per_item_validation_errors(http_node):
    body = {
        "requests": [
            {"name": "", "uniqueKey": "k", "hits": 1, "limit": 5,
             "duration": 1000},
            {"name": "gw2", "uniqueKey": "", "hits": 1, "limit": 5,
             "duration": 1000},
            {"name": "gw2", "uniqueKey": "ok", "hits": 1, "limit": 5,
             "duration": 1000},
        ]
    }
    out = _post(http_node, "/v1/GetRateLimits", body)["responses"]
    assert "namespace" in out[0]["error"]
    assert "unique_key" in out[1]["error"]
    assert out[2]["error"] == ""


def test_malformed_body_is_client_error(http_node):
    for payload in (
        b"{not json",
        b"[]",
        b'{"requests": "nope"}',
        b'{"requests": [42]}',
        b'{"requests": [{"name": "a", "uniqueKey": "b", "hits": "zz"}]}',
        b"\xff\xfe\x00bad utf8",
    ):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(http_node, "/v1/GetRateLimits", payload)
        assert 400 <= e.value.code < 500, payload


def test_health_and_metrics_routes(http_node):
    with urllib.request.urlopen(
        http_node + "/v1/HealthCheck", timeout=10
    ) as r:
        h = json.loads(r.read())
    assert h["status"] == "healthy"
    assert h["peerCount"] == 1
    with urllib.request.urlopen(http_node + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "grpc_request_duration_milliseconds" in text
    with urllib.request.urlopen(
        http_node + "/v1/debug/stats", timeout=10
    ) as r:
        stats = json.loads(r.read())
    assert "distinct_keys_estimate" in json.dumps(stats)
