"""Distributed tracing + flight recorder (r16, serve/tracing.py).

Covers, bottom-up:

- traceparent parse/format and the GEBT wire extension round trip;
- sampling policy: head sampling, tail-capture arming, the rolling
  p99 retention threshold, the ring bound, and the disabled fast path
  (no trace, no ids);
- the stage-clock hook: STAGES.add forwards spans into the active
  trace only;
- the acceptance scenario: a three-node LocalCluster drives ONE
  sampled request through the GEB door with a NON-owned key and a
  single trace id yields spans covering edge/bridge, queue, device
  (annotated with batch size and ladder rung), and the peer-forward
  hop on BOTH nodes — the context survived the gRPC hop;
- the differential identity fuzz: GUBER_TRACE_SAMPLE=0 vs 1 produce
  byte-identical decisions over the full device pipeline (the
  r10/r13 fake-clock rig).
"""

import asyncio
import time

import numpy as np
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    PeerInfo,
    RateLimitReq,
)
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve import tracing
from gubernator_tpu.serve.backends import ExactBackend, TpuBackend
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import Instance

ADDR = "127.0.0.1:7988"


# -- context / wire format --------------------------------------------------


def test_traceparent_roundtrip():
    ctx = tracing.TraceContext(0xABCDEF0102030405060708090A0B0C0D, 0x11223344AABBCCDD, True)
    hdr = ctx.header()
    assert hdr == (
        "00-abcdef0102030405060708090a0b0c0d-11223344aabbccdd-01"
    )
    back = tracing.parse_traceparent(hdr)
    assert back is not None
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled is True
    unsampled = tracing.TraceContext(5, 7, False).header()
    assert tracing.parse_traceparent(unsampled).sampled is False


@pytest.mark.parametrize(
    "bad",
    [
        None,
        "",
        "garbage",
        "00-xyz-123-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # zero span id
        "00-" + "1" * 31 + "-" + "1" * 16 + "-01",  # short trace id
        "00-1-2-3-4",
    ],
)
def test_traceparent_malformed_degrades_to_none(bad):
    assert tracing.parse_traceparent(bad) is None


def test_gebt_wire_extension_roundtrip():
    from gubernator_tpu.serve.edge_bridge import (
        _WTRACE_EXT,
        _trace_ctx_from_ext,
    )

    ctx = tracing.TraceContext((1 << 127) | 42, (1 << 63) | 7, True)
    raw = _WTRACE_EXT.pack(
        ctx.trace_id.to_bytes(16, "big"), ctx.span_id, 1
    )
    back = _trace_ctx_from_ext(*_WTRACE_EXT.unpack(raw))
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled
    # zero ids degrade to untraced, never error
    assert _trace_ctx_from_ext(b"\0" * 16, 1, 1) is None


# -- sampling policy / flight recorder --------------------------------------


def test_disabled_tracer_allocates_nothing():
    t = tracing.Tracer(sample=0.0, slow_ms=0.0)
    assert not t.enabled
    assert t.begin("grpc") is None
    assert t.join("grpc", None) is None
    # with tracing fully OFF, even a remote SAMPLED context is ignored:
    # traceparent arrives on untrusted doors, and a client header must
    # not override the operator's policy
    assert t.join("peers", tracing.TraceContext(9, 9, True)) is None
    # any enabled policy (tail capture alone suffices) honors it
    t2 = tracing.Tracer(sample=0.0, slow_ms=5.0)
    tr = t2.join("peers", tracing.TraceContext(9, 9, True))
    assert tr is not None and tr.sampled and tr.trace_id == 9
    # ...but an unsampled remote context still only tail-arms
    armed = t2.join("peers", tracing.TraceContext(9, 9, False))
    assert armed is not None and not armed.sampled


def test_head_sampling_and_recorder():
    t = tracing.Tracer(sample=1.0)
    tr = t.begin("geb")
    assert tr is not None and tr.sampled
    tr.add_span("bridge_decode", duration_s=0.001)
    tr.add_span("device", duration_s=0.002, batch=8, rung=16)
    t.finish(tr)
    snap = t.recorder.snapshot()
    assert snap["counters"]["recorded"] == 1
    doc = snap["traces"][0]
    assert doc["sampled"] and not doc["tail"]
    names = [s["name"] for s in doc["spans"]]
    assert names == ["bridge_decode", "device"]
    dev = doc["spans"][1]
    assert dev["annotations"] == {"batch": 8, "rung": 16}
    # by-id lookup round-trips
    assert t.recorder.get(doc["trace_id"])["span_id"] == doc["span_id"]
    assert t.recorder.get("f" * 32) is None


def test_tail_capture_retains_only_slow_requests():
    t = tracing.Tracer(sample=0.0, slow_ms=10.0)
    assert t.enabled
    fast = t.begin("http")
    assert fast is not None and not fast.sampled
    t.finish(fast)  # ~0ms: below the floor, not retained
    slow = t.begin("http")
    slow.t0 -= 0.05  # pretend it took 50ms
    t.finish(slow)
    snap = t.recorder.snapshot()
    assert snap["counters"]["recorded"] == 1
    assert snap["counters"]["tail_captured"] == 1
    assert snap["traces"][0]["tail"] is True
    assert snap["traces"][0]["duration_ms"] >= 10.0
    # unsampled traces never propagate a header
    assert slow.header() is None


def test_rolling_p99_lifts_the_threshold():
    t = tracing.Tracer(sample=0.0, slow_ms=1.0)
    # feed enough finishes that the p99 recompute (every 64) sees a
    # spread: most ~0ms, a few at ~100ms
    for i in range(200):
        tr = t.begin("grpc")
        if i % 50 == 0:
            tr.t0 -= 0.1
        t.finish(tr)
    assert t.recorder.threshold_ms() > 1.0  # p99 lifted off the floor


def test_recorder_ring_bound_and_reset():
    t = tracing.Tracer(sample=1.0, capacity=4)
    for _ in range(10):
        t.finish(t.begin("grpc"))
    snap = t.recorder.snapshot()
    assert snap["count"] == 4
    assert snap["counters"]["dropped"] == 6
    # limit=0 means counters-only, never "the whole ring" ([-0:] trap)
    assert t.recorder.snapshot(limit=0)["traces"] == []
    assert len(t.recorder.snapshot(limit=2)["traces"]) == 2
    t.recorder.reset()
    snap = t.recorder.snapshot()
    assert snap["count"] == 0 and snap["counters"]["recorded"] == 0


def test_lazy_ids_and_scope():
    t = tracing.Tracer(sample=0.0, slow_ms=5.0)
    tr = t.begin("geb")
    assert tr._trace_id is None  # armed, no id generated yet
    with tracing.scope(t, tr) as active:
        assert tracing.active() is active
    assert tracing.active() is None
    # the fast finish retained nothing and still never generated ids
    assert tr._trace_id is None


def test_stage_clock_forwards_spans_into_active_trace():
    from gubernator_tpu.serve.stages import STAGES

    t = tracing.Tracer(sample=1.0)
    tr = t.begin("geb")
    tok = tracing.activate(tr)
    try:
        STAGES.add("shed", 0.003)
    finally:
        tracing.deactivate(tok)
    STAGES.add("shed", 0.004)  # no active trace: stage clock only
    with tr._lock:
        spans = list(tr._spans)
    assert len(spans) == 1
    name, s, e, _ann = spans[0]
    assert name == "shed" and (e - s) == pytest.approx(0.003, abs=1e-6)


# -- GEBT over the frame service --------------------------------------------


def _mk_instance_coro(backend, **conf_kw):
    async def mk():
        conf = ServerConfig(
            grpc_address=ADDR, advertise_address=ADDR, **conf_kw
        )
        inst = Instance(conf, backend)
        inst.start()
        await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
        return inst

    return mk()


def test_hello_advertises_trace_capability():
    from gubernator_tpu.client_geb import parse_hello_bytes
    from gubernator_tpu.serve.edge_bridge import FrameService

    async def run():
        inst = await _mk_instance_coro(ExactBackend(1000))
        try:
            hello = parse_hello_bytes(
                FrameService(inst).hello_bytes()
            )
            assert hello.trace
        finally:
            await inst.stop()

    asyncio.run(run())


def test_gebt_frame_joins_remote_trace():
    """A GEBT frame's carried SAMPLED context is honored with the
    server's own HEAD sampling off (any enabled policy — here tail
    capture — suffices), and the retained trace keeps the client's
    trace id + parent span id — the cross-process contract."""
    from gubernator_tpu.client_geb import build_frame
    from gubernator_tpu.serve.edge_bridge import FrameService

    async def run():
        inst = await _mk_instance_coro(
            ExactBackend(1000), trace_slow_ms=60_000
        )
        try:
            svc = FrameService(inst)
            ctx = tracing.TraceContext(0xDEADBEEF, 0xFEED, True)
            frame, _ = build_frame(
                [RateLimitReq(name="t", unique_key="k", hits=1,
                              limit=5, duration=1000)],
                fast=False, windowed=True, frame_id=3,
                trace_ctx=ctx,
            )
            await svc.serve_frame_bytes(frame)
            snap = inst.tracer.recorder.snapshot()
            assert snap["counters"]["recorded"] == 1
            doc = snap["traces"][0]
            assert doc["trace_id"] == "%032x" % 0xDEADBEEF
            assert doc["parent_span_id"] == "%016x" % 0xFEED
            names = {s["name"] for s in doc["spans"]}
            assert "bridge_decode" in names
            assert "device" in names
        finally:
            await inst.stop()

    asyncio.run(run())


# -- acceptance: three-node cluster, one sampled request --------------------


def test_three_node_trace_covers_both_sides_of_the_forward():
    """ISSUE 12 acceptance: one sampled request through the GEB door
    with a NON-owned key; a single trace id yields spans covering
    edge/bridge + peer-forward on the origin node and queue + device
    (annotated with batch size and ladder rung) on the owner — the
    context survived the gRPC hop into the owner's own recorder."""
    from _util import free_ports
    from gubernator_tpu.client_geb import AsyncGebClient
    from gubernator_tpu.cluster import LocalCluster

    g1, g2, g3, geb = free_ports(4)
    cluster = LocalCluster(
        [f"127.0.0.1:{p}" for p in (g1, g2, g3)],
        backend_factory=lambda: TpuBackend(
            StoreConfig(rows=16, slots=1 << 8), buckets=(16,)
        ),
        geb_ports=[geb, 0, 0],
        trace_sample=1.0,
    )
    cluster.start()
    try:
        inst0 = cluster.instance_at(0)
        # a key node 0 does NOT own (forwarded over gRPC to its owner)
        key = next(
            k
            for k in (f"trace-k{i}" for i in range(256))
            if not inst0.get_peer(f"t_{k}").is_owner
        )
        owner_host = inst0.get_peer(f"t_{key}").host
        owner_idx = cluster.addresses.index(owner_host)
        assert owner_idx != 0

        async def drive():
            client = AsyncGebClient(
                f"127.0.0.1:{geb}", mode="string"
            )
            async with client:
                return await client.get_rate_limits(
                    [RateLimitReq(name="t", unique_key=key, hits=1,
                                  limit=100, duration=60_000)],
                    timeout=30.0,
                )

        (resp,) = asyncio.run(drive())
        assert not resp.error
        assert resp.metadata.get("owner") == owner_host

        def recorded(idx):
            return cluster.instance_at(idx).tracer.recorder.snapshot()[
                "traces"
            ]

        # recorders fill just after the response writes; poll briefly
        deadline = time.monotonic() + 10.0
        origin = owner = None
        while time.monotonic() < deadline:
            origin_traces = [
                t for t in recorded(0) if t["door"] == "geb"
            ]
            if origin_traces:
                origin = origin_traces[-1]
                owner_traces = [
                    t
                    for t in recorded(owner_idx)
                    if t["trace_id"] == origin["trace_id"]
                ]
                if owner_traces:
                    owner = owner_traces[-1]
                    break
            time.sleep(0.05)
        assert origin is not None, "origin node recorded no geb trace"
        assert owner is not None, (
            "owner node holds no trace with the origin's id — context "
            "lost on the gRPC hop"
        )

        # ONE trace id, spans covering the whole path across the two
        # recorders
        origin_names = {s["name"] for s in origin["spans"]}
        owner_names = {s["name"] for s in owner["spans"]}
        assert "bridge_decode" in origin_names  # edge/bridge
        assert "peer_forward" in origin_names  # the hop
        assert "batch_queue" in owner_names  # queue
        assert "device" in owner_names  # device
        assert owner["door"] == "peers"
        fwd = next(
            s for s in origin["spans"] if s["name"] == "peer_forward"
        )
        assert fwd["annotations"]["peer"] == owner_host
        dev = next(
            s for s in owner["spans"] if s["name"] == "device"
        )
        # device span annotated with batch size and ladder rung
        assert dev["annotations"]["batch"] >= 1
        assert dev["annotations"]["rung"] == 16  # the (16,) ladder
        assert "algo_mix" in dev["annotations"]
    finally:
        cluster.stop()


# -- differential identity fuzz ---------------------------------------------


class FakeClock:
    def __init__(self, t=1_700_000_000_000):
        self.t = t

    def __call__(self):
        return self.t


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


def _fuzz_stream(rng, keys, steps):
    for step in range(steps):
        n = int(rng.integers(1, 7))
        batch = []
        for _ in range(n):
            k = int(rng.integers(len(keys)))
            batch.append(
                RateLimitReq(
                    name="tracefuzz",
                    unique_key=keys[k],
                    hits=int(rng.choice([0, 1, 1, 1, 2, 9])),
                    limit=int(rng.choice([1, 1, 2, 3, 50])),
                    duration=int(rng.choice([400, 2000, 60_000])),
                    algorithm=Algorithm(k % 4),
                )
            )
        yield step, batch, int(rng.choice([0, 0, 1, 7, 150, 500, 2500]))


def _assert_same(a, b, ctx):
    assert (
        a.status, a.limit, a.remaining, a.reset_time, a.error
    ) == (
        b.status, b.limit, b.remaining, b.reset_time, b.error
    ), (ctx, a, b)


@pytest.mark.parametrize("seed", [6, 13])
def test_differential_identity_fuzz_tracing(monkeypatch, seed):
    """GUBER_TRACE_SAMPLE=0 is byte-identical to sample=1 (+ tail
    capture) over the full device pipeline: instance -> batcher (queue
    marks, device spans) -> arrival prep -> kernel. Tracing observes;
    it must never decide."""
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    def be():
        return TpuBackend(
            StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
        )

    async def run():
        on = await _mk_instance_coro(
            be(), trace_sample=1.0, trace_slow_ms=0.0001
        )
        off = await _mk_instance_coro(be())
        assert on.tracer.enabled and not off.tracer.enabled
        try:
            rng = np.random.default_rng(seed)
            keys = [f"t{i}" for i in range(12)]
            for step, batch, dt in _fuzz_stream(rng, keys, 120):
                clock.t += dt
                # the traced side runs under an active door trace,
                # exactly as the servicers set one up
                trace = on.tracer.begin("grpc")
                with tracing.scope(on.tracer, trace):
                    a = await on.get_rate_limits(batch)
                b = await off.get_rate_limits(batch)
                for x, y, r in zip(a, b, batch):
                    _assert_same(x, y, (step, r))
            rec = on.tracer.recorder
            assert rec.recorded > 0, "fuzz never recorded a trace"
        finally:
            await on.stop()
            await off.stop()

    asyncio.run(run())
