"""Differential tests: the TPU decide kernel vs the exact oracle.

Every behavioral contract the oracle encodes must hold identically on the
device path (same status, remaining, reset_time), batch after batch, under
a synthetic clock. Intra-batch duplicate-key semantics follow the
documented cumulative-attempt rule (kernels.py module docstring) and match
sequential-greedy for uniform hits.
"""

import random

import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    RateLimitResp,
    Status,
    SECOND,
)
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.engine import TpuEngine
from gubernator_tpu.core.oracle import get_rate_limit
from gubernator_tpu.core.store import StoreConfig

T0 = 1_700_000_000_000


@pytest.fixture(scope="module")
def engine():
    return TpuEngine(StoreConfig(rows=4, slots=1 << 12), buckets=(16, 64, 256))


@pytest.fixture(autouse=True)
def _reset(engine):
    engine.reset()
    yield


def req(**kw):
    kw.setdefault("name", "test")
    kw.setdefault("unique_key", "account:1234")
    return RateLimitReq(**kw)


def one(engine, r, now, gnp=False):
    return engine.get_rate_limits([r], now=now, gnp=[gnp])[0]


def check_same(resp: RateLimitResp, want: RateLimitResp, ctx=""):
    assert resp.status == want.status, ctx
    assert resp.limit == want.limit, ctx
    assert resp.remaining == want.remaining, ctx
    assert resp.reset_time == want.reset_time, ctx


# ---------------------------------------------------------------- behavioral


def test_over_the_limit(engine):
    expects = [(1, Status.UNDER_LIMIT), (0, Status.UNDER_LIMIT), (0, Status.OVER_LIMIT)]
    for remaining, status in expects:
        rl = one(engine, req(hits=1, limit=2, duration=SECOND), now=T0)
        assert (rl.remaining, rl.status) == (remaining, status)
        assert rl.limit == 2
        assert rl.reset_time == T0 + SECOND


def test_token_window_reset(engine):
    r = req(hits=1, limit=2, duration=5)
    assert one(engine, r, now=T0).remaining == 1
    assert one(engine, r, now=T0).remaining == 0
    rl = one(engine, r, now=T0 + 6)
    assert (rl.remaining, rl.status) == (1, Status.UNDER_LIMIT)


def test_leaky_drain(engine):
    steps = [
        (5, 0, 0, Status.UNDER_LIMIT),
        (1, 0, 0, Status.OVER_LIMIT),
        (1, 10, 0, Status.UNDER_LIMIT),
        (1, 20, 1, Status.UNDER_LIMIT),
    ]
    t = T0
    for hits, advance, want_rem, want_status in steps:
        t += advance
        rl = one(
            engine,
            req(hits=hits, limit=5, duration=50, algorithm=Algorithm.LEAKY_BUCKET),
            now=t,
        )
        assert rl.status == want_status, (hits, advance)
        assert rl.remaining == want_rem, (hits, advance)
        assert rl.limit == 5


def test_sticky_over_on_oversized_creation(engine):
    rl = one(engine, req(hits=10, limit=5, duration=SECOND), now=T0)
    assert (rl.status, rl.remaining) == (Status.OVER_LIMIT, 5)
    rl = one(engine, req(hits=2, limit=5, duration=SECOND), now=T0)
    assert (rl.status, rl.remaining) == (Status.OVER_LIMIT, 3)


def test_leaky_peek_at_empty_reports_over(engine):
    lk = dict(limit=5, duration=SECOND, algorithm=Algorithm.LEAKY_BUCKET)
    one(engine, req(hits=5, **lk), now=T0)
    rl = one(engine, req(hits=0, **lk), now=T0)
    assert rl.status == Status.OVER_LIMIT
    assert rl.reset_time != 0


def test_algorithm_switch_recreates_as_token(engine):
    one(
        engine,
        req(hits=1, limit=5, duration=SECOND, algorithm=Algorithm.LEAKY_BUCKET),
        now=T0,
    )
    rl = one(engine, req(hits=1, limit=5, duration=SECOND), now=T0)
    assert rl.remaining == 4  # fresh token window

    engine.reset()
    one(engine, req(hits=3, limit=5, duration=SECOND), now=T0)
    rl = one(
        engine,
        req(hits=1, limit=5, duration=SECOND, algorithm=Algorithm.LEAKY_BUCKET),
        now=T0,
    )
    assert rl.remaining == 4  # recreated as fresh *token* bucket
    assert rl.reset_time == T0 + SECOND


def test_zero_limit_token(engine):
    rl = one(engine, req(hits=1, limit=0, duration=10_000), now=T0)
    assert rl.status == Status.OVER_LIMIT
    assert rl.remaining == 0


def test_global_replica_read(engine):
    # owner broadcast installs a replica; gnp reads serve it verbatim
    engine.update_globals(
        [
            (
                "test_account:g1",
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=5, remaining=4,
                    reset_time=T0 + 3000,
                ),
            )
        ],
        now=T0,
    )
    r = RateLimitReq(
        name="test", unique_key="account:g1", hits=1, limit=5, duration=3000
    )
    r_key = r.hash_key()
    assert r_key == "test_account:g1"
    rl = one(engine, r, now=T0, gnp=True)
    check_same(
        rl,
        RateLimitResp(
            status=Status.UNDER_LIMIT, limit=5, remaining=4, reset_time=T0 + 3000
        ),
    )
    # replica unchanged by the read
    rl = one(engine, r, now=T0, gnp=True)
    assert rl.remaining == 4


def test_global_replica_miss_processes_locally(engine):
    r = req(unique_key="account:g2", hits=1, limit=5, duration=3000)
    rl = one(engine, r, now=T0, gnp=True)
    assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 4)
    # the local (owned-style) entry now serves as the replica
    rl = one(engine, r, now=T0, gnp=True)
    assert rl.remaining == 4


# ------------------------------------------------------ intra-batch semantics


def test_batch_duplicate_uniform_hits(engine):
    rs = [req(hits=1, limit=3, duration=SECOND) for _ in range(5)]
    resp = engine.get_rate_limits(rs, now=T0)
    got = [(r.status, r.remaining) for r in resp]
    assert got == [
        (Status.UNDER_LIMIT, 2),
        (Status.UNDER_LIMIT, 1),
        (Status.UNDER_LIMIT, 0),
        (Status.OVER_LIMIT, 0),
        (Status.OVER_LIMIT, 0),
    ]


def test_batch_duplicate_oversized_does_not_starve(engine):
    rs = [
        req(hits=2, limit=5, duration=SECOND),
        req(hits=100, limit=5, duration=SECOND),  # refused outright
        req(hits=3, limit=5, duration=SECOND),  # still admitted
    ]
    resp = engine.get_rate_limits(rs, now=T0)
    assert resp[0].status == Status.UNDER_LIMIT
    assert resp[1].status == Status.OVER_LIMIT
    assert resp[2].status == Status.UNDER_LIMIT
    assert resp[2].remaining == 0


def test_batch_refused_duplicates_do_not_poison_sticky(engine):
    # Refused duplicates inflate the attempted prefix but consume nothing;
    # the persisted sticky-OVER flag must track *real* depletion only.
    rs = [req(hits=3, limit=5, duration=SECOND) for _ in range(3)]
    resp = engine.get_rate_limits(rs, now=T0)
    assert [r.status for r in resp] == [
        Status.UNDER_LIMIT, Status.OVER_LIMIT, Status.OVER_LIMIT,
    ]
    # Store remaining is 2; a later small request must succeed UNDER_LIMIT.
    rl = one(engine, req(hits=1, limit=5, duration=SECOND), now=T0 + 1)
    assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 1)


def test_batch_leaky_refused_follower_reset_uses_request_duration(engine):
    lk = dict(limit=1, duration=60_000, algorithm=Algorithm.LEAKY_BUCKET)
    rs = [req(hits=1, **lk), req(hits=1, **lk)]
    resp = engine.get_rate_limits(rs, now=T0)
    assert resp[0].status == Status.UNDER_LIMIT
    assert resp[1].status == Status.OVER_LIMIT
    # retry hint is one leak interval (duration/limit), not a stale slot's
    assert resp[1].reset_time == T0 + 60_000


def test_batch_distinct_keys_independent(engine):
    rs = [
        req(unique_key=f"k{i}", hits=1, limit=2, duration=SECOND)
        for i in range(10)
    ]
    resp = engine.get_rate_limits(rs, now=T0)
    assert all(r.remaining == 1 for r in resp)
    resp = engine.get_rate_limits(rs, now=T0)
    assert all(r.remaining == 0 for r in resp)


# ------------------------------------------------------------- differential


def _random_req(rng, keys):
    algo = rng.choice([Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET])
    return RateLimitReq(
        name="fuzz",
        unique_key=rng.choice(keys),
        hits=rng.choice([0, 1, 1, 1, 2, 3, 7, 50]),
        limit=rng.choice([1, 2, 5, 20]),
        duration=rng.choice([10, 100, 1000]),
        algorithm=algo,
    )


def test_differential_fuzz_vs_oracle(engine):
    """Random single-key-per-batch workload over an advancing clock: the
    device path must match the oracle decision-for-decision."""
    rng = random.Random(1234)
    keys = [f"acct:{i}" for i in range(40)]
    cache = LRUCache()
    now = T0
    for step in range(400):
        now += rng.choice([0, 1, 3, 7, 15, 40, 200])
        # unique keys within the batch so oracle sequencing matches exactly
        batch_keys = rng.sample(keys, rng.randint(1, 12))
        rs = []
        for k in batch_keys:
            r = _random_req(rng, [k])
            rs.append(r)
        got = engine.get_rate_limits(rs, now=now)
        for r, g in zip(rs, got):
            want = get_rate_limit(cache, r, now=now)
            check_same(g, want, ctx=f"step={step} key={r.unique_key} req={r}")


def test_differential_sequential_same_key(engine):
    """Long same-key request sequences (one per batch) across both
    algorithms and window resets."""
    rng = random.Random(99)
    cache = LRUCache()
    now = T0
    for algo in (Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET):
        engine.reset()
        cache = LRUCache()
        for step in range(200):
            now += rng.choice([0, 1, 2, 5, 11, 37])
            r = RateLimitReq(
                name="seq",
                unique_key="only",
                hits=rng.choice([0, 1, 1, 2, 4, 9]),
                limit=8,
                duration=60,
                algorithm=algo,
            )
            got = one(engine, r, now=now)
            want = get_rate_limit(cache, r, now=now)
            check_same(got, want, ctx=f"algo={algo} step={step} now={now}")


def test_eviction_recreates_window(engine):
    """Overfilling the store evicts oldest-expiry entries; evicted keys are
    simply recreated (the reference's accepted over-admission contract)."""
    small = TpuEngine(StoreConfig(rows=2, slots=16), buckets=(64,))
    rs = [
        req(unique_key=f"spill:{i}", hits=1, limit=5, duration=SECOND)
        for i in range(32)
    ]
    resp = small.get_rate_limits(rs, now=T0)
    assert all(r.remaining == 4 for r in resp)
    # 32 keys in a 2x16 store: many were evicted; recreated windows give
    # remaining == 4 again instead of 3 (over-admission, never a crash)
    resp = small.get_rate_limits(rs, now=T0 + 1)
    assert all(r.remaining in (3, 4) for r in resp)
    assert any(r.remaining == 4 for r in resp)


# ------------------------------------------------------- presorted kernel


def test_presorted_equals_wrapper_with_interspersed_invalids():
    """decide_presorted under the caller contract (host-sorted rows,
    padding repeats the last key, invalid rows possibly interspersed as
    the mesh's ownership masking produces) matches the self-sorting
    decide() wrapper row for row, and writes the same store."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gubernator_tpu.core.kernels import (
        BatchRequest,
        decide,
        decide_presorted,
    )
    from gubernator_tpu.core.store import (
        StoreConfig,
        group_sort_key_np,
        new_store,
    )

    rng = np.random.default_rng(7)
    cfg = StoreConfig(rows=16, slots=1 << 10)
    B, n = 64, 50

    for trial in range(4):
        keys = rng.integers(1, 2**63, n, dtype=np.int64).astype(np.uint64)
        keys = keys[rng.integers(0, n, n)]  # force duplicate keys
        hits = rng.integers(0, 4, n).astype(np.int64)
        limit = rng.integers(1, 6, n).astype(np.int64)
        duration = np.full(n, 60_000, np.int64)
        algo = rng.integers(0, 2, n).astype(np.int32)
        # per-key validity mask (the mesh masks whole key groups)
        key_valid = {k: bool(rng.random() < 0.7) for k in set(keys.tolist())}
        valid_n = np.asarray([key_valid[k] for k in keys.tolist()])

        # --- host-sorted presorted request, padding repeats last row ----
        skey = group_sort_key_np(keys, cfg.slots)
        order = np.argsort(skey, kind="stable")

        def pad(x, fill_from_last=True):
            out = np.empty(B, x.dtype)
            out[:n] = x[order]
            out[n:] = out[n - 1]
            return out

        valid = np.zeros(B, bool)
        valid[:n] = valid_n[order]
        req_sorted = BatchRequest(
            key_hash=jnp.asarray(pad(keys)),
            hits=jnp.asarray(pad(hits.astype(np.int32))),
            limit=jnp.asarray(pad(limit.astype(np.int32))),
            duration=jnp.asarray(pad(duration.astype(np.int32))),
            algo=jnp.asarray(pad(algo)),
            gnp=jnp.zeros(B, bool),
            valid=jnp.asarray(valid),
        )

        # --- same batch, original order, through the wrapper ------------
        def pad0(x, dtype):
            out = np.zeros(B, dtype)
            out[:n] = x
            return out

        valid0 = np.zeros(B, bool)
        valid0[:n] = valid_n
        req_orig = BatchRequest(
            key_hash=jnp.asarray(pad0(keys, np.uint64)),
            hits=jnp.asarray(pad0(hits, np.int32)),
            limit=jnp.asarray(pad0(limit, np.int32)),
            duration=jnp.asarray(pad0(duration, np.int32)),
            algo=jnp.asarray(pad0(algo, np.int32)),
            gnp=jnp.zeros(B, bool),
            valid=jnp.asarray(valid0),
        )

        now = jnp.int32(1000 + trial)
        s1, r1, st1 = jax.jit(decide_presorted)(
            new_store(cfg), req_sorted, now
        )
        s2, r2, st2 = jax.jit(decide)(new_store(cfg), req_orig, now)

        # unpermute the presorted responses host-side
        for f in ("status", "limit", "remaining", "reset_time"):
            a = np.asarray(getattr(r1, f))[:n]
            u = np.empty_like(a)
            u[order] = a
            b = np.asarray(getattr(r2, f))[:n]
            np.testing.assert_array_equal(
                u[valid_n], b[valid_n], err_msg=f"{f} trial={trial}"
            )
        np.testing.assert_array_equal(
            np.asarray(s1.data), np.asarray(s2.data), err_msg="store"
        )
        assert int(st1.hits) == int(st2.hits)
        assert int(st1.misses) == int(st2.misses)


def test_pallas_sweep_matches_scatter():
    """The opt-in pallas store-sweep writeback must be bit-identical to
    the XLA scatter-add on way-disjoint delta rows (interpret mode on
    CPU; scripts run the same check compiled on real TPU)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gubernator_tpu.core import pallas_sweep as ps

    rng = np.random.default_rng(11)
    buckets, B = 1 << 10, 2048
    data = rng.integers(-2**31, 2**31 - 1, (buckets, 128), dtype=np.int64
                        ).astype(np.int32)
    bkt = np.sort(rng.integers(0, buckets, B)).astype(np.int32)
    # way-disjoint rows: each duplicate run takes a distinct 8-lane way
    drow = np.zeros((B, 128), np.int32)
    run = 0
    vals = rng.integers(-2**31, 2**31 - 1, (B, 8), dtype=np.int64
                        ).astype(np.int32)
    for i in range(B):
        run = run + 1 if i and bkt[i] == bkt[i - 1] else 0
        w = run % 16
        if rng.random() < 0.7:
            drow[i, w * 8 : (w + 1) * 8] = vals[i]
    want = data.copy()
    np.add.at(want, bkt, drow)

    got = ps._apply_inline(
        jnp.asarray(data), jnp.asarray(bkt), jnp.asarray(drow),
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_writeback_auto_selection(monkeypatch):
    """GUBER_WRITEBACK=auto (default) picks the pallas sweep exactly in
    its measured winning regime (B >= 4x bucket count, see
    scripts/bench_sweep_regime.py) and the scatter elsewhere; explicit
    values force a path."""
    import jax as jax_mod

    from gubernator_tpu.core.kernels import _use_sweep_writeback

    monkeypatch.delenv("GUBER_WRITEBACK", raising=False)
    # auto never picks the Mosaic TPU kernel on a non-TPU backend
    assert not _use_sweep_writeback(2048, 128, 16384)
    # ... the regime assertions below model a TPU host
    monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
    # flagship store (32k buckets, 32k batch): density 1 -> scatter
    assert not _use_sweep_writeback(1 << 15, 128, 1 << 15)
    # dense small-store regime: density >= 4 -> sweep
    assert _use_sweep_writeback(2048, 128, 16384)
    assert _use_sweep_writeback(4096, 128, 32768)
    # shape constraints still gate the sweep even in its regime
    assert not _use_sweep_writeback(2048, 64, 16384)  # W != 128
    assert not _use_sweep_writeback(100, 128, 16384)  # buckets % 128

    monkeypatch.setenv("GUBER_WRITEBACK", "scatter")
    assert not _use_sweep_writeback(2048, 128, 16384)
    monkeypatch.setenv("GUBER_WRITEBACK", "sweep")
    assert _use_sweep_writeback(1 << 15, 128, 16384)
