"""DeviceBatcher unit tests: the pipelined submit/wait flusher.

The serving claim under test: with a backend exposing
decide_submit/decide_wait, the flusher submits batch N+1 while batch N's
fetch is still in flight (throughput tracks max(host, device) per batch,
not the sum), submits stay strictly serialized, a failed fetch fails only
its own batch, and backends without the split still work unchanged.
"""

import asyncio
import threading

import pytest

from gubernator_tpu.api.types import RateLimitReq, RateLimitResp
from gubernator_tpu.serve.batcher import DeviceBatcher


def _req(i: int) -> RateLimitReq:
    return RateLimitReq(
        name="b", unique_key=f"k{i}", hits=1, limit=10, duration=1000
    )


class PipelinedFake:
    """Records submit/wait interleaving; waits block until released."""

    def __init__(self):
        self.submits = []
        self.waits = []
        self.releases = {}
        self.lock = threading.Lock()
        self.concurrent_submits = 0
        self.fail_wait_for = set()

    def decide_submit(self, reqs, gnp, now=None):
        with self.lock:
            self.concurrent_submits += 1
            assert self.concurrent_submits == 1, "submits must serialize"
        try:
            idx = len(self.submits)
            self.submits.append([r.unique_key for r in reqs])
            self.releases[idx] = threading.Event()
            return (idx, list(reqs))
        finally:
            with self.lock:
                self.concurrent_submits -= 1

    def decide_wait(self, handle):
        idx, reqs = handle
        assert self.releases[idx].wait(timeout=30), (
            f"fetch {idx} never released"
        )
        self.waits.append(idx)
        if idx in self.fail_wait_for:
            raise RuntimeError(f"fetch {idx} failed")
        return [RateLimitResp(limit=r.limit, remaining=7) for r in reqs]


@pytest.fixture()
def loop_run():
    def run(coro):
        return asyncio.run(coro)

    return run


def test_pipelined_overlap_and_order(loop_run):
    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(be, batch_wait=0, batch_limit=1)
        b.start()
        t1 = asyncio.ensure_future(b.decide([_req(1)], [False]))
        # first batch submitted; its fetch now blocks on releases[0]
        while len(be.submits) < 1:
            await asyncio.sleep(0.001)
        t2 = asyncio.ensure_future(b.decide([_req(2)], [False]))
        # the second batch must be SUBMITTED while fetch 0 is in flight —
        # this is the pipelining property
        while len(be.submits) < 2:
            await asyncio.sleep(0.001)
        assert be.waits == []  # nothing fetched yet
        be.releases[0].set()
        r1 = await t1
        be.releases[1].set()
        r2 = await t2
        assert [r.remaining for r in r1] == [7]
        assert [r.remaining for r in r2] == [7]
        # both fetches resolved (their completion order is the release
        # order here, but the contract no longer promises ordering:
        # fetch_depth-wide pools complete out of order by design)
        assert sorted(be.waits) == [0, 1]
        await b.stop()

    loop_run(scenario())


def test_fetch_depth_bounds_inflight_and_allows_overlap(loop_run):
    """fetch_depth=3: three batches submit back-to-back with none
    fetched; the fourth submit stalls until one fetch completes. Fetches
    completing out of order resolve their own batches independently."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(be, batch_wait=0, batch_limit=1, fetch_depth=3)
        b.start()
        tasks = [
            asyncio.ensure_future(b.decide([_req(i)], [False]))
            for i in range(4)
        ]
        while len(be.submits) < 3:
            await asyncio.sleep(0.001)
        # depth reached: the 4th submit must be parked
        await asyncio.sleep(0.05)
        assert len(be.submits) == 3
        assert be.waits == []
        # release the MIDDLE batch first: it resolves alone and frees a
        # slot for batch 3
        be.releases[1].set()
        r1 = await tasks[1]
        assert [r.remaining for r in r1] == [7]
        while len(be.submits) < 4:
            await asyncio.sleep(0.001)
        for i in (0, 2, 3):
            be.releases.setdefault(i, threading.Event()).set()
        for i in (0, 2, 3):
            assert [r.remaining for r in await tasks[i]] == [7]
        await b.stop()

    loop_run(scenario())


def test_batch_limit_never_overshoots_group_parked(loop_run):
    """A group that would push the batch past batch_limit is parked and
    ships in the NEXT batch: the flattened batch the backend sees never
    exceeds the limit (the engine's bucket ladder is sized to it)."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(be, batch_wait=0.02, batch_limit=5, fetch_depth=4)
        b.start()
        t1 = asyncio.ensure_future(
            b.decide([_req(i) for i in range(3)], [False] * 3)
        )
        t2 = asyncio.ensure_future(
            b.decide([_req(10 + i) for i in range(4)], [False] * 4)
        )
        # 3 + 4 > 5: the second group must ship alone in batch 2
        while len(be.submits) < 2:
            await asyncio.sleep(0.001)
            for k, ev in list(be.releases.items()):
                ev.set()
        assert [len(s) for s in be.submits] == [3, 4]
        for k, ev in list(be.releases.items()):
            ev.set()
        r1, r2 = await t1, await t2
        assert len(r1) == 3 and len(r2) == 4
        await b.stop()

    loop_run(scenario())


def test_failed_fetch_fails_only_its_batch(loop_run):
    async def scenario():
        be = PipelinedFake()
        be.fail_wait_for.add(0)
        b = DeviceBatcher(be, batch_wait=0, batch_limit=1)
        b.start()
        t1 = asyncio.ensure_future(b.decide([_req(1)], [False]))
        while len(be.submits) < 1:
            await asyncio.sleep(0.001)
        be.releases[0].set()
        with pytest.raises(RuntimeError, match="fetch 0 failed"):
            await t1
        # the flusher survives: the next batch decides normally
        t2 = asyncio.ensure_future(b.decide([_req(2)], [False]))
        while len(be.submits) < 2:
            await asyncio.sleep(0.001)
        be.releases[1].set()
        r2 = await t2
        assert [r.remaining for r in r2] == [7]
        await b.stop()

    loop_run(scenario())


def test_stop_with_two_batches_in_flight(loop_run):
    """stop() while batch N is fetching and batch N+1 is already
    submitted (the flusher parked awaiting the previous fetch) must
    resolve BOTH batches' callers and return cleanly — not strand
    futures or re-raise CancelledError out of stop()."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(be, batch_wait=0, batch_limit=1)
        b.start()
        t1 = asyncio.ensure_future(b.decide([_req(1)], [False]))
        # wait until batch 1 is OWNED by a fetch task (submits alone
        # can be observed before the flusher receives the handle, and a
        # stop() landing in that window legitimately fails the batch)
        while not b._pending:
            await asyncio.sleep(0.001)
        t2 = asyncio.ensure_future(b.decide([_req(2)], [False]))
        while len(b._pending) < 2:
            await asyncio.sleep(0.001)
        stop_task = asyncio.ensure_future(b.stop())
        await asyncio.sleep(0.01)  # let the cancel land mid-pipeline
        be.releases[0].set()
        be.releases[1].set()
        await stop_task  # must not raise
        r1, r2 = await t1, await t2
        assert [r.remaining for r in r1] == [7]
        assert [r.remaining for r in r2] == [7]
        assert sorted(be.waits) == [0, 1]

    loop_run(scenario())


def test_stop_drains_inflight_fetch(loop_run):
    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(be, batch_wait=0, batch_limit=1)
        b.start()
        t1 = asyncio.ensure_future(b.decide([_req(1)], [False]))
        while not b._pending:  # a fetch task owns the batch
            await asyncio.sleep(0.001)
        be.releases[0].set()
        # stop() must await the in-flight fetch so t1 resolves, not hang
        await b.stop()
        r1 = await t1
        assert [r.remaining for r in r1] == [7]

    loop_run(scenario())


def test_stop_fails_requests_parked_in_collect_window(loop_run):
    """stop() while the flusher is still collecting (parked in the
    batch_wait window with one request already popped from the queue)
    must fail that caller with an error — not strand it forever."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(be, batch_wait=5.0, batch_limit=100)
        b.start()
        t1 = asyncio.ensure_future(b.decide([_req(1)], [False]))
        await asyncio.sleep(0.05)  # flusher now parked in the window
        assert be.submits == []  # nothing flushed yet
        await b.stop()
        with pytest.raises(RuntimeError, match="stopped mid-batch"):
            await t1

    loop_run(scenario())


def test_deep_batch_accumulates_while_pipeline_full(loop_run):
    """Throughput mode (deep_batch=True): while every fetch_depth slot
    is occupied a flush could not submit anyway, so the collector keeps
    accumulating; the moment a slot frees, everything accumulated ships
    as ONE deep batch instead of a run of shallow ones."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(
            be, batch_wait=0, batch_limit=100, fetch_depth=1,
            deep_batch=True,
        )
        b.start()
        t0 = asyncio.ensure_future(b.decide([_req(0)], [False]))
        # batch 0 submitted; the single pipeline slot is now occupied
        while not b._pending:
            await asyncio.sleep(0.001)
        t1 = asyncio.ensure_future(b.decide([_req(1)], [False]))
        t2 = asyncio.ensure_future(b.decide([_req(2)], [False]))
        # the flusher must HOLD these (pipeline full), not submit them
        await asyncio.sleep(0.05)
        assert len(be.submits) == 1, be.submits
        be.releases[0].set()
        await t0
        # slot freed -> the held groups flush together as one deep batch
        while len(be.submits) < 2:
            await asyncio.sleep(0.001)
        assert be.submits[1] == ["k1", "k2"], be.submits
        be.releases[1].set()
        r1, r2 = await t1, await t2
        assert [r.remaining for r in r1 + r2] == [7, 7]
        await b.stop()

    loop_run(scenario())


def test_deep_batch_idle_flush_semantics_unchanged(loop_run):
    """Deep mode must not change idle-path latency: with no batch in
    flight the hold predicate is False, so a solo request flushes after
    exactly the historical drain + batch_wait window — it is never held
    hostage to traffic that may not come."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(
            be, batch_wait=0, batch_limit=100_000, fetch_depth=2,
            deep_batch=True,
        )
        b.start()
        t1 = asyncio.ensure_future(b.decide([_req(1)], [False]))
        # idle pipeline: the solo request must submit promptly
        for _ in range(200):
            if be.submits:
                break
            await asyncio.sleep(0.001)
        assert be.submits == [["k1"]]
        be.releases[0].set()
        assert [r.remaining for r in await t1] == [7]
        await b.stop()

    loop_run(scenario())


def test_deep_batch_respects_batch_limit(loop_run):
    """Accumulation stops at batch_limit: a group that would overshoot
    parks in carry and ships in the NEXT deep batch."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(
            be, batch_wait=0, batch_limit=3, fetch_depth=1,
            deep_batch=True,
        )
        b.start()
        t0 = asyncio.ensure_future(b.decide([_req(0)], [False]))
        while not b._pending:
            await asyncio.sleep(0.001)
        tasks = [
            asyncio.ensure_future(b.decide([_req(10 + i)], [False]))
            for i in range(4)
        ]
        await asyncio.sleep(0.05)
        assert len(be.submits) == 1
        be.releases[0].set()
        await t0
        while len(be.submits) < 2:
            await asyncio.sleep(0.001)
        assert be.submits[1] == ["k10", "k11", "k12"]  # capped at 3
        be.releases[1].set()
        while len(be.submits) < 3:
            await asyncio.sleep(0.001)
            for k, ev in list(be.releases.items()):
                ev.set()
        for t in tasks:
            await t
        await b.stop()

    loop_run(scenario())


class BlockingFake:
    """A backend with only the blocking decide() — the fallback path."""

    def __init__(self):
        self.calls = 0

    def decide(self, reqs, gnp, now=None):
        self.calls += 1
        return [RateLimitResp(limit=r.limit, remaining=3) for r in reqs]


def test_non_pipelined_backend_fallback(loop_run):
    async def scenario():
        be = BlockingFake()
        b = DeviceBatcher(be, batch_wait=0, batch_limit=8)
        b.start()
        out = await b.decide([_req(i) for i in range(5)], [False] * 5)
        assert [r.remaining for r in out] == [3] * 5
        assert be.calls == 1  # coalesced into one backend call
        await b.stop()

    loop_run(scenario())


def test_decide_after_stop_raises(loop_run):
    """A closed batcher fails fast instead of enqueueing into a queue no
    flusher reads (the caller would await a future that never resolves)."""

    async def scenario():
        be = PipelinedFake()
        b = DeviceBatcher(be, batch_wait=0)
        b.start()
        await b.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            await b.decide([_req(0)], [False])
        with pytest.raises(RuntimeError, match="stopped"):
            await b.update_globals([("k", RateLimitResp(limit=1))])

    loop_run(scenario())


def test_update_globals_coalesce_one_backend_call(loop_run):
    """r10 satellite: all `globals` groups of one flush batch land in
    ONE backend.update_globals call (one to_thread hop instead of N),
    in enqueue order, with per-caller futures still resolved
    individually — and failed individually when the coalesced call
    raises."""

    class Recorder:
        def __init__(self):
            self.calls = []
            self.fail_next = False

        def decide(self, reqs, gnp):
            return [RateLimitResp(limit=r.limit) for r in reqs]

        def update_globals(self, updates):
            self.calls.append([k for k, _ in updates])
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("install exploded")

    async def scenario():
        be = Recorder()
        b = DeviceBatcher(be, batch_wait=0.05, batch_limit=100)
        b.start()
        # three caller groups enqueue inside one straggler window ->
        # one flush batch -> ONE backend call with all six keys
        tasks = [
            asyncio.ensure_future(
                b.update_globals(
                    [
                        (f"g{i}a", RateLimitResp(limit=1)),
                        (f"g{i}b", RateLimitResp(limit=1)),
                    ]
                )
            )
            for i in range(3)
        ]
        await asyncio.gather(*tasks)
        assert len(be.calls) == 1, be.calls
        assert be.calls[0] == [
            "g0a", "g0b", "g1a", "g1b", "g2a", "g2b"
        ]
        # a coalesced-call failure fails EVERY caller group's future
        be.fail_next = True
        fails = [
            asyncio.ensure_future(
                b.update_globals([(f"f{i}", RateLimitResp(limit=1))])
            )
            for i in range(2)
        ]
        results = await asyncio.gather(*fails, return_exceptions=True)
        assert all(
            isinstance(r, RuntimeError) and "exploded" in str(r)
            for r in results
        ), results
        await b.stop()

    loop_run(scenario())


def test_inline_fast_path_never_overtakes_collected_items(loop_run):
    """An inline decide must not run ahead of work the flusher already
    drained into its batch while parked in a batch_wait straggler
    window (the queue looks empty then, but earlier work exists)."""

    class InlineRecorder:
        inline_decide = True

        def __init__(self):
            self.order = []

        def decide(self, reqs, gnp):
            self.order.append(("D", [r.unique_key for r in reqs]))
            return [RateLimitResp(limit=r.limit) for r in reqs]

        def update_globals(self, updates):
            self.order.append(("U", [k for k, _ in updates]))

    async def scenario():
        be = InlineRecorder()
        b = DeviceBatcher(be, batch_wait=0.2, batch_limit=100)
        b.start()
        # U enters the queue; the flusher drains it into its batch and
        # parks in the 200ms straggler window (queue now empty)
        u_task = asyncio.ensure_future(
            b.update_globals([("k", RateLimitResp(limit=1))])
        )
        await asyncio.sleep(0.05)
        assert b._live_batch, "flusher should hold U in its open batch"
        # D arrives mid-window: the fast path must refuse; D coalesces
        # into the same batch and executes AFTER U
        resps = await b.decide([_req(1)], [False])
        await u_task
        await b.stop()
        assert resps[0].limit == 10
        assert [kind for kind, _ in be.order] == ["U", "D"], be.order

    loop_run(scenario())


def test_inline_fast_path_concurrency_soak(loop_run):
    """Randomized soak of the inline fast path against the flusher: many
    concurrent decide/update_globals callers with a nonzero batch_wait,
    exercising every path (fast path, coalesced batches, straggler
    windows, interleaved global installs).

    What this pins: liveness (no deadlock/hang between the fast path
    and the flusher) and exactly-once application — 300 decides on one
    key yield the complete multiset of remaining values {100..399},
    so no hit is lost or double-applied under any interleaving, and
    every caller gets a real response through stop().

    What this deliberately does NOT pin: fast-path/flusher ORDERING.
    A sorted multiset is order-invariant, and no black-box soak can
    see the overtake hazard anyway — overtaking items whose callers
    are still awaiting is a legal concurrent serialization; the guard
    exists for FIFO fairness and is pinned white-box by
    test_inline_fast_path_never_overtakes_collected_items above."""

    import random

    from gubernator_tpu.serve.backends import ExactBackend

    async def scenario():
        rng = random.Random(7)
        be = ExactBackend(1000)
        b = DeviceBatcher(be, batch_wait=0.002, batch_limit=64)
        b.start()

        LIMIT = 400

        async def one_decide(i):
            await asyncio.sleep(rng.random() * 0.05)
            r = RateLimitReq(
                name="soak", unique_key="k", hits=1, limit=LIMIT,
                duration=60_000,
            )
            return (await b.decide([r], [False]))[0]

        async def one_update(i):
            await asyncio.sleep(rng.random() * 0.05)
            # replica install for an UNRELATED key: must never perturb
            # the soak key's countdown
            await b.update_globals(
                [(f"other:{i}", RateLimitResp(limit=5, remaining=2))]
            )

        tasks = []
        for i in range(300):
            tasks.append(one_decide(i))
            if i % 7 == 0:
                tasks.append(one_update(i))
        outs = await asyncio.gather(*tasks)
        await b.stop()

        remainings = sorted(
            r.remaining for r in outs if isinstance(r, RateLimitResp)
        )
        # 300 decides, limit 400: remaining values must be exactly
        # {100..399}, each consumed once — duplicates or gaps mean a
        # lost or double-applied hit
        assert remainings == list(range(LIMIT - 300, LIMIT)), (
            remainings[:10], remainings[-10:], len(remainings)
        )

    loop_run(scenario())
