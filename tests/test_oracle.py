"""Algorithm-level unit tests for the exact oracle.

The reference only tests algorithms through its gRPC surface
(reference functional_test.go); these tests encode the same behavioral
contracts directly at the algorithm layer, plus the quirk semantics the
survey calls out.
"""

import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    Status,
    SECOND,
    MILLISECOND,
)
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.oracle import get_rate_limit, leaky_bucket, token_bucket


def req(**kw):
    kw.setdefault("name", "test")
    kw.setdefault("unique_key", "account:1234")
    return RateLimitReq(**kw)


def test_over_the_limit():
    # Mirrors reference functional_test.go:51-95.
    cache = LRUCache()
    expects = [
        (1, Status.UNDER_LIMIT),
        (0, Status.UNDER_LIMIT),
        (0, Status.OVER_LIMIT),
    ]
    for remaining, status in expects:
        rl = token_bucket(
            cache, req(hits=1, limit=2, duration=SECOND), now=1_000_000
        )
        assert rl.remaining == remaining
        assert rl.status == status
        assert rl.limit == 2
        assert rl.reset_time != 0


def test_token_bucket_window_reset():
    # Mirrors reference functional_test.go:97-146 with explicit clocks.
    cache = LRUCache()
    now = 1_000_000
    r = req(hits=1, limit=2, duration=5 * MILLISECOND)
    rl = token_bucket(cache, r, now=now)
    assert (rl.remaining, rl.status) == (1, Status.UNDER_LIMIT)
    rl = token_bucket(cache, r, now=now)
    assert (rl.remaining, rl.status) == (0, Status.UNDER_LIMIT)
    # Past the 5ms window the entry lazily expires and is recreated.
    rl = token_bucket(cache, r, now=now + 6)
    assert (rl.remaining, rl.status) == (1, Status.UNDER_LIMIT)


def test_leaky_bucket_drain():
    # Mirrors reference functional_test.go:148-206 with explicit clocks.
    cache = LRUCache()
    now = 1_000_000
    steps = [
        # (hits, advance_ms_before, want_remaining, want_status)
        (5, 0, 0, Status.UNDER_LIMIT),
        (1, 0, 0, Status.OVER_LIMIT),
        (1, 10, 0, Status.UNDER_LIMIT),
        (1, 20, 1, Status.UNDER_LIMIT),
    ]
    t = now
    for hits, advance, want_rem, want_status in steps:
        t += advance
        rl = leaky_bucket(
            cache, req(hits=hits, limit=5, duration=50 * MILLISECOND), now=t
        )
        assert rl.status == want_status, (hits, advance)
        assert rl.remaining == want_rem, (hits, advance)
        assert rl.limit == 5


def test_zero_duration_and_zero_limit():
    # Mirrors reference functional_test.go:208-269 items 1-2.
    cache = LRUCache()
    rl = token_bucket(cache, req(hits=1, limit=10, duration=0), now=1_000_000)
    assert rl.status == Status.UNDER_LIMIT
    rl = token_bucket(
        cache, req(unique_key="account:12345", hits=1, limit=0, duration=10000),
        now=1_000_000,
    )
    assert rl.status == Status.OVER_LIMIT


def test_token_peek_does_not_charge():
    cache = LRUCache()
    r = req(hits=1, limit=5, duration=SECOND)
    token_bucket(cache, r, now=1_000_000)
    peek = req(hits=0, limit=5, duration=SECOND)
    rl = token_bucket(cache, peek, now=1_000_000)
    assert rl.remaining == 4
    rl = token_bucket(cache, peek, now=1_000_000)
    assert rl.remaining == 4


def test_token_over_limit_not_persisted():
    # algorithms.go:27-31: a refused over-sized request does not consume.
    cache = LRUCache()
    token_bucket(cache, req(hits=1, limit=100, duration=SECOND), now=1_000_000)
    rl = token_bucket(cache, req(hits=1000, limit=100, duration=SECOND), now=1_000_000)
    assert rl.status == Status.OVER_LIMIT
    assert rl.remaining == 99
    rl = token_bucket(cache, req(hits=99, limit=100, duration=SECOND), now=1_000_000)
    assert rl.status == Status.UNDER_LIMIT
    assert rl.remaining == 0


def test_token_sticky_over_on_oversized_creation():
    # algorithms.go:77-81: creation with hits > limit persists OVER_LIMIT
    # with remaining = limit.
    cache = LRUCache()
    rl = token_bucket(cache, req(hits=10, limit=5, duration=SECOND), now=1_000_000)
    assert rl.status == Status.OVER_LIMIT
    assert rl.remaining == 5
    # Subsequent charge succeeds numerically but still reports the persisted
    # OVER_LIMIT status (cached-status reuse at algorithms.go:64-65).
    rl = token_bucket(cache, req(hits=2, limit=5, duration=SECOND), now=1_000_000)
    assert rl.status == Status.OVER_LIMIT
    assert rl.remaining == 3


def test_leaky_peek_at_empty_reports_over():
    # algorithms.go:129-151: the empty-bucket check precedes the peek check.
    cache = LRUCache()
    leaky_bucket(cache, req(hits=5, limit=5, duration=SECOND), now=1_000_000)
    rl = leaky_bucket(cache, req(hits=0, limit=5, duration=SECOND), now=1_000_000)
    assert rl.status == Status.OVER_LIMIT
    assert rl.reset_time != 0


def test_leaky_reset_time_zero_under_limit():
    cache = LRUCache()
    rl = leaky_bucket(cache, req(hits=1, limit=5, duration=SECOND), now=1_000_000)
    assert rl.status == Status.UNDER_LIMIT
    assert rl.reset_time == 0


def test_leaky_refused_request_advances_timestamp():
    # algorithms.go:118-121: refused hits still reset the leak clock.
    cache = LRUCache()
    now = 1_000_000
    leaky_bucket(cache, req(hits=5, limit=5, duration=50), now=now)  # empty
    # rate = 10ms; after 9ms nothing has leaked yet.
    rl = leaky_bucket(cache, req(hits=1, limit=5, duration=50), now=now + 9)
    assert rl.status == Status.OVER_LIMIT
    # The refused request at +9 reset the timestamp, so at +18 only 9ms have
    # "elapsed" since then — still nothing leaked.
    rl = leaky_bucket(cache, req(hits=1, limit=5, duration=50), now=now + 18)
    assert rl.status == Status.OVER_LIMIT
    # At +29 (11ms after the last), one token has leaked back.
    rl = leaky_bucket(cache, req(hits=1, limit=5, duration=50), now=now + 29)
    assert rl.status == Status.UNDER_LIMIT
    assert rl.remaining == 0


def test_algorithm_switch_recreates_as_token():
    # algorithms.go:33-38,100-105: both mismatch directions recreate as a
    # fresh token bucket.
    cache = LRUCache()
    leaky_bucket(cache, req(hits=1, limit=5, duration=SECOND), now=1_000_000)
    rl = token_bucket(cache, req(hits=1, limit=5, duration=SECOND), now=1_000_000)
    assert rl.remaining == 4  # fresh window, not remaining from leaky

    cache = LRUCache()
    token_bucket(cache, req(hits=3, limit=5, duration=SECOND), now=1_000_000)
    rl = leaky_bucket(cache, req(hits=1, limit=5, duration=SECOND), now=1_000_000)
    # Fresh *token* bucket: remaining = limit - hits.
    assert rl.remaining == 4
    assert rl.reset_time != 0  # token creation sets reset_time


def test_dispatch_invalid_algorithm():
    cache = LRUCache()
    r = req(hits=1, limit=5, duration=SECOND)
    r.algorithm = 7
    with pytest.raises(ValueError):
        get_rate_limit(cache, r)


def test_lru_eviction():
    cache = LRUCache(max_size=3)
    now = 1_000_000
    for i in range(4):
        token_bucket(
            cache, req(unique_key=f"k{i}", hits=1, limit=5, duration=SECOND), now=now
        )
    assert len(cache) == 3
    # k0 was evicted: a new request recreates the window.
    rl = token_bucket(
        cache, req(unique_key="k0", hits=1, limit=5, duration=SECOND), now=now
    )
    assert rl.remaining == 4
