"""Membership-churn soak for the cluster edge's riskiest machinery.

The r5 lane/ring code has three moving parts that only interleave
under churn: the refresher republishing rings, publish_ring evicting
lanes whose endpoint left (shutdown -> queued-shard failure -> detached
workers freeing the Lane), and in-flight execute() calls racing both.
This soak flaps the membership every ~60 ms for several seconds while
4 client threads hammer the edge, then asserts:

- the edge NEVER crashes or wedges (every request gets an HTTP
  response within timeout for the whole soak);
- every item answer is either a real decision or one of the two
  legitimate transient errors (stale-ring retry / bridge unreachable)
  — never garbage, never a protocol desync;
- after the flapping stops, the edge converges: requests succeed with
  no errors and BOTH bridges serve fast traffic again.
"""

import asyncio
import json
import pathlib
import subprocess
import threading
import time
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.serve.edge_bridge import EdgeBridge
from tests._util import edge_binary, free_ports

ROOT = pathlib.Path(__file__).resolve().parent.parent
EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

NODE_A = "10.98.0.1:81"
NODE_B = "10.98.0.2:81"


class FakePicker:
    def __init__(self, hosts_self):
        self._peers = [
            type("P", (), {"host": h, "is_owner": mine})()
            for h, mine in hosts_self
        ]

    def peers(self):
        return self._peers


class Inst:
    def __init__(self, self_host, hosts):
        class FakeBackend:
            decide_submit_arrays = object()
            decide_submit = object()

        self.backend = FakeBackend()
        self.picker = FakePicker([(h, h == self_host) for h in hosts])
        inst = self

        class B:
            async def decide_arrays(self, fields, frame=True):
                n = fields["key_hash"].shape[0]
                inst.fast_items += n
                return (
                    np.zeros(n, np.int64),
                    fields["limit"],
                    fields["limit"] - fields["hits"],
                    np.zeros(n, np.int64),
                )

        class T:
            def observe_hashes(self, h):
                pass

        self.batcher = B()
        self.traffic = T()
        self.fast_items = 0

    async def get_rate_limits(self, reqs, stage_frame=False):
        from gubernator_tpu.api.types import RateLimitResp, Status

        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=r.limit - r.hits, reset_time=1,
            )
            for r in reqs
        ]


OK_ERRORS = ("membership changed", "unreachable", "edge backend")


def _post(port, tag, n=8, timeout=30):
    body = json.dumps(
        {
            "requests": [
                {"name": "cs", "uniqueKey": f"{tag}-{i}", "hits": 1,
                 "limit": 7, "duration": 60000}
                for i in range(n)
            ]
        }
    ).encode()
    resp = urllib.request.urlopen(
        urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/GetRateLimits", data=body,
            headers={"Content-Type": "application/json"},
        ),
        timeout=timeout,
    )
    return json.loads(resp.read())


def test_membership_flapping_soak():
    edge_http, bridge_b_tcp = free_ports(2)
    sock_a = "/tmp/guber-churn-a.sock"

    async def main():
        import os

        inst_a = Inst(NODE_A, [NODE_A])
        inst_b = Inst(NODE_B, [NODE_A, NODE_B])
        bridge_a = EdgeBridge(
            inst_a, sock_a,
            peer_bridges={NODE_B: f"127.0.0.1:{bridge_b_tcp}"},
        )
        bridge_b = EdgeBridge(
            inst_b, "", tcp_address=f"127.0.0.1:{bridge_b_tcp}"
        )
        try:
            os.unlink(sock_a)
        except FileNotFoundError:
            pass
        await bridge_a.start()
        await bridge_b.start()
        edge = subprocess.Popen(
            [str(EDGE_BIN), "--listen", str(edge_http),
             "--backend", sock_a, "--ring-refresh-ms", "60",
             "--batch-wait-us", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        stats = {
            "calls": 0, "errors": 0, "unavail": 0, "bad": [], "fails": []
        }
        stop = threading.Event()

        def client(w):
            import urllib.error

            i = 0
            while not stop.is_set():
                i += 1
                try:
                    out = _post(edge_http, f"w{w}-{i}")
                except urllib.error.HTTPError as e:
                    # 503 mid-flap is a legitimate transient (a lane
                    # reconnect window on a churning ring); anything
                    # else is not
                    stats["calls"] += 1
                    if e.code == 503:
                        stats["unavail"] += 1
                    else:
                        stats["bad"].append(f"HTTP {e.code}")
                    continue
                except Exception as e:  # timeout/conn error = wedge
                    stats["fails"].append(repr(e))
                    return
                stats["calls"] += 1
                for r in out["responses"]:
                    if r["error"]:
                        stats["errors"] += 1
                        if not any(s in r["error"] for s in OK_ERRORS):
                            stats["bad"].append(r["error"])
                    elif r["remaining"] != "6":
                        stats["bad"].append(f"remaining={r['remaining']}")

        try:
            import socket as sl

            deadline = time.monotonic() + 10
            while True:
                if edge.poll() is not None:
                    pytest.fail(f"edge died:\n{edge.stdout.read()}")
                try:
                    sl.create_connection(
                        ("127.0.0.1", edge_http), timeout=1
                    ).close()
                    break
                except OSError:
                    assert time.monotonic() < deadline
                    await asyncio.sleep(0.05)

            # 4 clients, not 16: the fake bridges share this box's ONE
            # core with the clients' GIL, and an over-dense soak mostly
            # measures starvation of the fake asyncio loop (5s lane
            # connect/hello timeouts pile into client-visible stalls)
            threads = [
                threading.Thread(target=client, args=(w,))
                for w in range(4)
            ]
            for t in threads:
                t.start()

            # flap the membership for ~4s: 1-node <-> 2-node ring
            one = FakePicker([(NODE_A, True)])
            two = FakePicker([(NODE_A, True), (NODE_B, False)])
            end = time.monotonic() + 4.0
            flip = False
            while time.monotonic() < end:
                inst_a.picker = two if flip else one
                flip = not flip
                await asyncio.sleep(0.06)
            inst_a.picker = two  # settle on the 2-node ring
            await asyncio.sleep(1.5)
            stop.set()
            # join OFF the loop thread: the fake bridges live on THIS
            # event loop, and a blocking join here deadlocks the
            # clients' final in-flight requests against their own
            # teardown (they stall until the edge's peer timeout
            # rescues them — the first version of this test diagnosed
            # exactly that as a spurious edge wedge)
            await asyncio.to_thread(
                lambda: [t.join(timeout=30) for t in threads]
            )
            assert not any(t.is_alive() for t in threads), "client wedged"
            assert edge.poll() is None, f"edge died:\n{edge.stdout.read()}"
            assert stats["fails"] == [], stats["fails"][:3]
            assert stats["bad"] == [], stats["bad"][:5]
            assert stats["calls"] > 100, stats

            # convergence: clean request, both bridges fast again (the
            # settled ring must also stop producing 503s)
            b_before = inst_b.fast_items
            deadline = time.monotonic() + 8
            clean = False
            import urllib.error

            while time.monotonic() < deadline:
                try:
                    out = await asyncio.to_thread(
                        _post, edge_http, f"conv-{time.monotonic_ns()}",
                        30,
                    )
                except urllib.error.HTTPError:
                    await asyncio.sleep(0.1)
                    continue
                if all(not r["error"] for r in out["responses"]):
                    if inst_b.fast_items > b_before:
                        clean = True
                        break
                await asyncio.sleep(0.1)
            assert clean, (
                f"no clean fast convergence (b fast {inst_b.fast_items})"
            )
        finally:
            stop.set()
            edge.kill()
            await bridge_a.stop()
            await bridge_b.stop()

    asyncio.run(main())
