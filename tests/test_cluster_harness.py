"""Unit tests for the in-process cluster harness itself — the reference
unit-tests its harness too (reference cluster/cluster_test.go:26-221:
peer bookkeeping, start/stop, bad-address startup failure). Round 1
shipped the harness with zero direct coverage (VERDICT weak: only the
functional suite's happy path exercised it)."""

import socket

import pytest

from gubernator_tpu.cluster import LocalCluster

import grpc

from gubernator_tpu.api.grpc_glue import V1Stub
from gubernator_tpu.api.proto.gen import gubernator_pb2


from _util import free_ports as _free_ports


def test_start_serves_and_stop_terminates():
    addrs = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    c = LocalCluster(addrs)
    c.start()
    try:
        # both nodes answer a real gRPC health check
        for a in addrs:
            with grpc.insecure_channel(a) as chan:
                resp = V1Stub(chan).HealthCheck(
                    gubernator_pb2.HealthCheckReq(), timeout=5
                )
                assert resp.status == "healthy"
                assert resp.peer_count == 2
    finally:
        c.stop()
    assert c._thread is None or not c._thread.is_alive()
    assert c.servers == []
    # the ports are released (a new bind succeeds)
    for a in addrs:
        host, _, port = a.rpartition(":")
        with socket.socket() as s:
            s.bind((host, int(port)))


def test_peer_accessors():
    addrs = [f"127.0.0.1:{p}" for p in _free_ports(3)]
    c = LocalCluster(addrs)
    c.start()
    try:
        assert [c.peer_at(i) for i in range(3)] == addrs
        assert all(c.get_peer() in addrs for _ in range(10))
        for i in range(3):
            inst = c.instance_at(i)
            assert inst is c.servers[i].instance
            assert inst.health_check().peer_count == 3
    finally:
        c.stop()


def test_bad_address_fails_startup():
    """An unbindable address must surface as a startup error, not a hang
    (reference cluster_test.go: StartWith with a bad address errors)."""
    c = LocalCluster(["256.256.256.256:1"])
    with pytest.raises(Exception):
        c.start(timeout=30)
    c.stop()  # must be safe after failed start


def test_restart_after_stop():
    addrs = [f"127.0.0.1:{p}" for p in _free_ports(1)]
    c = LocalCluster(addrs)
    c.start()
    c.stop()
    c.start()  # same harness object restarts cleanly
    try:
        with grpc.insecure_channel(addrs[0]) as chan:
            resp = V1Stub(chan).HealthCheck(
                gubernator_pb2.HealthCheckReq(), timeout=5
            )
            assert resp.status == "healthy"
    finally:
        c.stop()
