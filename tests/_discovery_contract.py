"""The discovery client API contract: the exact calls serve/discovery.py
makes on etcd3 / kubernetes clients, as bindable call shapes.

This is the single source the drift checks assert against from BOTH
directions (r2 verdict: the fakes encoded the builder's assumed API
shapes and had never met the real libraries):

- tests/test_discovery.py asserts the FAKES accept exactly these calls;
- tests/test_discovery_real.py (gated on the real packages being
  installed) asserts the REAL libraries accept them too.

Pinned against python-etcd3 0.12.x and kubernetes>=24 (see
pyproject.toml [project.optional-dependencies] discovery). Prose
documentation: docs/discovery_api_contract.md.

Each entry: method name -> (positional_args, keyword_args) exactly as
the production code calls it. A library or fake whose signature cannot
bind the call shape has drifted.
"""

import inspect

_SENTINEL = object()

# etcd3.client(...) constructor: every argument passed by keyword
# (discovery.py EtcdPool.__init__)
ETCD_CLIENT_CTOR_CALL = (
    (),
    {
        "host": "127.0.0.1",
        "port": 2379,
        "ca_cert": "ca.pem",
        "cert_cert": "c.pem",
        "cert_key": "k.pem",
    },
)

# methods EtcdPool calls on the client object, with their call shapes
ETCD_CLIENT_CALLS = {
    # self._lease = client.lease(LEASE_TTL_S)
    "lease": ((30,), {}),
    # client.put(key, value, lease=lease)
    "put": (("k", "v"), {"lease": _SENTINEL}),
    # client.delete(key)
    "delete": (("k",), {}),
    # client.get_prefix(prefix) -> iterable of (value_bytes, metadata);
    # ONLY element [0] (the value bytes) is consumed
    "get_prefix": (("p",), {}),
    # client.watch_prefix(prefix) -> (events_iterator, cancel_callable)
    "watch_prefix": (("p",), {}),
}

# methods EtcdPool calls on the lease object
ETCD_LEASE_CALLS = {
    "refresh": ((), {}),
}

# kubernetes surface K8sPool uses
K8S_API_CALLS = {
    # api.list_namespaced_endpoints(namespace, label_selector=...)
    # (called through watch.stream, which forwards args verbatim)
    "list_namespaced_endpoints": (("ns",), {"label_selector": "app=x"}),
}
K8S_WATCH_CALLS = {
    # watch.stream(func, namespace, label_selector=...) yields events
    # shaped {"object": V1Endpoints}
    "stream": ((_SENTINEL, "ns"), {"label_selector": "app=x"}),
    # watch.stop() ends the blocking stream
    "stop": ((), {}),
}
# attribute path K8sPool reads off each event object:
#   endpoints.subsets[].addresses[].ip
K8S_ENDPOINTS_ATTRS = ("subsets", "addresses", "ip")


def assert_binds(fn, call, where: str, unbound: bool = False) -> None:
    """The production call shape must bind to fn's signature. `unbound`
    prepends a self placeholder (for checking class-level functions)."""
    args, kwargs = call
    if unbound:
        args = (_SENTINEL,) + tuple(args)
    try:
        inspect.signature(fn).bind(*args, **kwargs)
    except TypeError as e:
        raise AssertionError(
            f"{where}: production call shape args={args} kwargs="
            f"{sorted(kwargs)} does not bind to signature "
            f"{inspect.signature(fn)} — the discovery contract "
            f"(tests/_discovery_contract.py) and the implementation have "
            f"drifted: {e}"
        ) from None


def assert_object_implements(
    obj, calls: dict, where: str, unbound: bool = False
) -> None:
    for name, call in calls.items():
        fn = getattr(obj, name, None)
        assert callable(fn), f"{where}: missing method {name}()"
        assert_binds(fn, call, f"{where}.{name}", unbound=unbound)
