"""Mixed fleet through the cluster edge: array-backend + exact-backend
nodes in ONE ring.

A rolling migration (or deliberate mixed deployment) puts nodes with
and without the pre-hashed fast path in the same cluster. The router's
contract (edge.cc Router::execute): items owned by a fast-capable peer
ride GEB6 to that peer's bridge; items owned by a non-fast peer fold
into the string path, where the primary's instance forwards them over
gRPC — per ITEM, silently, with identical decisions either way.

Topology here: node 0 (edge's primary) and node 1 run the tpu backend
(fast-capable); node 2 runs the exact backend (no array path — its
bridge hello advertises slow). Assertions:

- every key decides exactly once with correct remaining, whoever owns
  it (no errors, no double-admission);
- node 1 serves fast items (its edge_fast_items_total grows by its
  exact ownership share) while node 2 serves NONE over the fast path
  (counter stays 0) yet still owns its share — proven by reading its
  keys back through node 2 directly;
- owner metadata appears for remote-owned items regardless of path.
"""

import json
import os
import pathlib
import subprocess
import sys
import time
import urllib.request

import pytest

from tests._util import edge_binary

ROOT = pathlib.Path(__file__).resolve().parent.parent
EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

BASE = 19580
GRPC_ADDRS = [f"127.0.0.1:{BASE + i}" for i in range(3)]
HTTP_PORTS = [BASE + 10 + i for i in range(3)]
BRIDGE_PORTS = [BASE + 20 + i for i in range(3)]
EDGE_HTTP = BASE + 30
SOCKS = [f"/tmp/guber-edge-mixed-{i}.sock" for i in range(3)]
BACKENDS = ["tpu", "tpu", "exact"]


@pytest.fixture(scope="module")
def fleet():
    peers = ",".join(GRPC_ADDRS)
    bridges = ",".join(
        f"{GRPC_ADDRS[i]}=127.0.0.1:{BRIDGE_PORTS[i]}" for i in range(3)
    )
    daemons = []
    for i in range(3):
        try:
            os.unlink(SOCKS[i])
        except FileNotFoundError:
            pass
        env = dict(
            os.environ,
            PYTHONPATH=str(ROOT),
            GUBER_BACKEND=BACKENDS[i],
            GUBER_JAX_PLATFORM="cpu",
            GUBER_STORE_SLOTS=str(1 << 10),
            GUBER_GRPC_ADDRESS=GRPC_ADDRS[i],
            GUBER_HTTP_ADDRESS=f"127.0.0.1:{HTTP_PORTS[i]}",
            GUBER_ADVERTISE_ADDRESS=GRPC_ADDRS[i],
            GUBER_PEERS=peers,
            GUBER_EDGE_SOCKET=SOCKS[i],
            GUBER_EDGE_TCP=f"127.0.0.1:{BRIDGE_PORTS[i]}",
            GUBER_EDGE_PEER_BRIDGES=bridges,
            JAX_COMPILATION_CACHE_DIR=str(ROOT / ".jax_cache_cpu"),
        )
        daemons.append(
            subprocess.Popen(
                [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=ROOT, env=env,
            )
        )
    deadline = time.monotonic() + 240
    for i, d in enumerate(daemons):
        while not os.path.exists(SOCKS[i]):
            if d.poll() is not None:
                for x in daemons:
                    x.kill()
                pytest.fail(f"daemon {i} died:\n{d.stdout.read()}")
            if time.monotonic() > deadline:
                for x in daemons:
                    x.kill()
                pytest.fail(f"daemon {i} boot timeout")
            time.sleep(0.2)
    edge = subprocess.Popen(
        [str(EDGE_BIN), "--listen", str(EDGE_HTTP), "--backend", SOCKS[0],
         "--ring-refresh-ms", "200"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    import socket as sl

    deadline = time.monotonic() + 10
    while True:
        if edge.poll() is not None:
            for d in daemons:
                d.kill()
            pytest.fail(f"edge died:\n{edge.stdout.read()}")
        try:
            sl.create_connection(("127.0.0.1", EDGE_HTTP), timeout=1).close()
            break
        except OSError:
            assert time.monotonic() < deadline
            time.sleep(0.05)
    # let the edge's peer lanes complete their hellos so fast routing
    # to node 1 is active before the measured traffic
    time.sleep(1.0)
    yield
    edge.kill()
    for d in daemons:
        d.terminate()
    for d in daemons:
        d.wait(timeout=10)


def _post(port, body):
    # bounded 503 retry (r15 deflake; see tests/_util.post_json)
    from _util import post_json

    return post_json(
        f"http://127.0.0.1:{port}/v1/GetRateLimits", body
    )


def _metric(node, name):
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{HTTP_PORTS[node]}/metrics", timeout=10
    ).read().decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return 0.0


def _owner(name, key):
    import bisect

    from gubernator_tpu.core.hashing import ring_hash

    points = sorted((ring_hash(a), a) for a in GRPC_ADDRS)
    keys = [p for p, _ in points]
    i = bisect.bisect_left(keys, ring_hash(f"{name}_{key}"))
    return points[i % len(keys)][1] if i < len(keys) else points[0][1]


def test_mixed_fleet_decides_once_and_degrades_per_item(fleet):
    keys = [f"mx-{i}" for i in range(120)]
    want = {a: [] for a in GRPC_ADDRS}
    for k in keys:
        want[_owner("mf", k)].append(k)
    # the spread must exercise all three nodes for the test to mean
    # anything (crc32 over 120 keys always does on this ring)
    assert all(want[a] for a in GRPC_ADDRS), {
        a: len(v) for a, v in want.items()
    }

    before_fast = [_metric(i, "edge_fast_items_total") for i in range(3)]
    out = _post(
        EDGE_HTTP,
        {"requests": [
            {"name": "mf", "uniqueKey": k, "hits": 1, "limit": 9,
             "duration": 60000}
            for k in keys
        ]},
    )
    for k, r in zip(keys, out["responses"]):
        assert r["error"] == "" and r["remaining"] == "8", (k, r)
        owner = _owner("mf", k)
        if owner == GRPC_ADDRS[0]:
            assert "owner" not in r["metadata"], (k, r)
        else:
            # remote-owned: owner metadata present whether the item
            # rode GEB6 (node 1) or the forwarded string path (node 2)
            assert r["metadata"].get("owner") == owner, (k, r)

    after_fast = [_metric(i, "edge_fast_items_total") for i in range(3)]
    # node 1 (fast-capable) served its exact share over GEB6
    assert after_fast[1] - before_fast[1] == len(want[GRPC_ADDRS[1]])
    # node 2 (exact backend) NEVER sees a pre-hashed frame
    assert after_fast[2] == before_fast[2] == 0.0
    # and yet owns its share: read its keys back through it directly
    out = _post(
        HTTP_PORTS[2],
        {"requests": [
            {"name": "mf", "uniqueKey": k, "hits": 0, "limit": 9,
             "duration": 60000}
            for k in want[GRPC_ADDRS[2]][:20]
        ]},
    )
    assert all(
        r["remaining"] == "8" and "owner" not in r["metadata"]
        for r in out["responses"]
    ), out["responses"]
