"""Tier-1 drift gate: every GUBER_* env knob the package reads must be
documented in example.conf AND docs/operations.md (r10 satellite; same
contract as the generated README tables, tests/test_readme_tables.py).
Run `python scripts/check_knobs.py` for the per-knob diff."""

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _mod():
    sys.path.insert(0, str(ROOT / "scripts"))
    try:
        import check_knobs
    finally:
        sys.path.pop(0)
    return check_knobs


def test_scanner_finds_real_knob_reads():
    knobs = _mod().read_knobs()
    # spot-check knobs read through every detection shape: _get(env,..)
    # helpers, os.environ.get, and the shed knobs this PR added
    for k in (
        "GUBER_BACKEND",
        "GUBER_FETCH_DEPTH",
        "GUBER_SHED_CACHE",
        "GUBER_SHED_CACHE_KEYS",
        "GUBER_SWEEP_TILE",
    ):
        assert k in knobs, (k, sorted(knobs))
    # prefix-only mentions must not count as knobs
    assert "GUBER_DIST_" not in knobs
    assert all(not k.endswith("_") for k in knobs)


def test_scanner_detects_every_read_shape():
    """The AST scanner must catch call-arg AND subscript reads, and
    ignore docstrings/comments — pinned on a synthetic module so a
    detection shape can't silently die (subscript detection did, on
    py3.9+'s unwrapped slice nodes)."""
    import ast as ast_mod

    mod = ast_mod.parse(
        '"""GUBER_DOCSTRING_ONLY"""\n'
        'import os\n'
        'a = os.environ.get("GUBER_VIA_GET")\n'
        'b = os.environ["GUBER_VIA_SUBSCRIPT"]\n'
        'c = env.get("GUBER_VIA_KWARG", default="x")\n'
    )
    ck = _mod()
    found = set()
    for node in ast_mod.walk(mod):
        found |= ck._knob_strings(node)
    assert found == {"GUBER_VIA_GET", "GUBER_VIA_SUBSCRIPT",
                     "GUBER_VIA_KWARG"}, found


def test_every_read_knob_is_documented():
    assert _mod().main() == 0, (
        "GUBER_* knob read in gubernator_tpu/ missing from example.conf "
        "or docs/operations.md — run scripts/check_knobs.py"
    )
