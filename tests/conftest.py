"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so that mesh-sharded code paths
(the v5e-8 story) are exercised without TPU hardware.

Note: this environment pre-imports jax at interpreter start (sitecustomize)
with JAX_PLATFORMS pointing at the TPU tunnel, so setting the env var here
is too late — the platform must be forced through jax.config before any
backend initializes. XLA_FLAGS is still read lazily at CPU-client init.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
