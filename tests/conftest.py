"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so that mesh-sharded code paths
(the v5e-8 story) are exercised without TPU hardware. This must be set before
jax is imported anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
