"""Packaged-client seam (VERDICT r5 gap 3): the client imports JAX-free.

An external consumer embedding `gubernator_tpu.client` (or just the API
types + generated stubs) must not drag the whole accelerator stack in:
the package root and the client subtree import grpc + protobuf only.
Asserted in a SUBPROCESS so this test is immune to whatever the rest of
the suite already imported.
"""

import subprocess
import sys

import pytest


def _run(code: str):
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_client_import_loads_no_jax():
    r = _run(
        "import sys\n"
        "import gubernator_tpu.client\n"
        "import gubernator_tpu  # the root re-exports the API types\n"
        "banned = [m for m in sys.modules if m == 'jax' "
        "or m.startswith('jax.') or m == 'jaxlib' "
        "or m.startswith('jaxlib.')]\n"
        "assert not banned, f'client import loaded {banned}'\n"
        "c = gubernator_tpu.client.V1Client('127.0.0.1:1')\n"
        "c.close()\n"
        "print('OK')\n"
    )
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_client_usable_with_jax_import_blocked():
    """Simulate a host with no JAX installed: poison the import and
    check the client still constructs requests + converts types."""
    r = _run(
        "import sys\n"
        "sys.modules['jax'] = None  # ImportError on any 'import jax'\n"
        "sys.modules['jaxlib'] = None\n"
        "from gubernator_tpu.client import V1Client, AsyncV1Client\n"
        "from gubernator_tpu.api.types import RateLimitReq\n"
        "from gubernator_tpu.api import convert\n"
        "pb = convert.req_to_pb(RateLimitReq(name='n', unique_key='k',\n"
        "    hits=1, limit=10, duration=1000))\n"
        "assert convert.req_from_pb(pb).unique_key == 'k'\n"
        "print('OK')\n"
    )
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_core_import_still_enables_x64():
    """The x64 flag moved from the package root to gubernator_tpu.core;
    every jax-touching path imports through core, so the flag must be on
    by the time any kernel code could trace."""
    r = _run(
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import gubernator_tpu.core  # noqa: F401\n"
        "assert jax.config.jax_enable_x64\n"
        "print('OK')\n"
    )
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
