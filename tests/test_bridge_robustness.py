"""Bridge-side robustness: frame-parse fuzz + randomized router shapes.

The r5 TCP listener (GUBER_EDGE_TCP) widens the bridge's exposure from
"same-host unix socket" to "cluster-internal network port". It is a
TRUSTED port (like PeersV1 — see serve/edge_bridge.py), but trusted
must still mean crash-proof: a confused peer, a version-skewed edge,
or a port scanner must cost one closed connection, never a daemon
fault or a wedged event loop.

Second half: randomized mixed-shape batches through the REAL edge
binary against counting fakes — GLOBAL items, empty names/keys, and
plain items interleaved at random, asserting every item answers
exactly once with the right value and the right path (string path for
GLOBAL/invalid, pre-hashed for the rest) across the split/fold router.
"""

import asyncio
import json
import random
import struct
import subprocess
import time
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.serve.edge_bridge import EdgeBridge
from tests._util import edge_binary, free_ports

EDGE_BIN = edge_binary()


class _ArrBackend:
    decide_submit_arrays = object()
    decide_submit = object()


class _Traffic:
    def observe_hashes(self, h):
        pass


class CountingInstance:
    def __init__(self, self_host, hosts, peer_map=None):
        self.backend = _ArrBackend()
        self.picker = type(
            "P",
            (),
            {
                "peers": lambda s: [
                    type("Q", (), {"host": h, "is_owner": h == self_host})()
                    for h in hosts
                ]
            },
        )()
        self.fast_items = 0
        self.slow_items = 0
        inst = self

        class B:
            async def decide_arrays(self, fields, frame=True):
                n = fields["key_hash"].shape[0]
                inst.fast_items += n
                return (
                    np.zeros(n, np.int64),
                    fields["limit"],
                    fields["limit"] - fields["hits"],
                    np.zeros(n, np.int64),
                )

        self.batcher = B()
        self.traffic = _Traffic()

    async def get_rate_limits(self, reqs, stage_frame=False):
        from gubernator_tpu.api.types import RateLimitResp, Status

        self.slow_items += len(reqs)
        out = []
        for r in reqs:
            if not r.unique_key:
                out.append(
                    RateLimitResp(error="field 'unique_key' cannot be empty")
                )
            elif not r.name:
                out.append(
                    RateLimitResp(error="field 'namespace' cannot be empty")
                )
            else:
                out.append(
                    RateLimitResp(
                        status=Status.UNDER_LIMIT, limit=r.limit,
                        remaining=r.limit - r.hits, reset_time=1,
                    )
                )
        return out


def test_bridge_survives_garbage_on_both_listeners():
    """Random bytes, truncated frames, oversized counts, and a valid
    hello-then-garbage sequence against the unix AND TCP listeners:
    every connection must end closed with the bridge still serving."""
    (tcp_port,) = free_ports(1)
    sock = "/tmp/guber-bridge-fuzz.sock"

    async def run():
        import os

        inst = CountingInstance("10.97.0.1:81", ["10.97.0.1:81"])
        bridge = EdgeBridge(
            inst, sock, tcp_address=f"127.0.0.1:{tcp_port}"
        )
        try:
            os.unlink(sock)
        except FileNotFoundError:
            pass
        await bridge.start()
        rng = random.Random(1234)
        try:
            async def connect(kind):
                if kind == "unix":
                    return await asyncio.wait_for(
                        asyncio.open_unix_connection(sock), 5
                    )
                return await asyncio.wait_for(
                    asyncio.open_connection("127.0.0.1", tcp_port), 5
                )

            for trial in range(40):
                kind = ("unix", "tcp")[trial % 2]
                reader, writer = await connect(kind)
                # consume the hello header so garbage lands mid-protocol
                await asyncio.wait_for(reader.readexactly(16), 5)
                shape = trial % 4
                if shape == 0:  # pure garbage
                    writer.write(rng.randbytes(rng.randint(1, 200)))
                elif shape == 1:  # valid magic, absurd counts
                    writer.write(
                        struct.pack(
                            "<II", 0x31424547, rng.randint(1 << 20, 1 << 30)
                        )
                        + struct.pack("<I", rng.randint(0, 1 << 16))
                        + rng.randbytes(64)
                    )
                elif shape == 2:  # GEB6 header then truncation
                    writer.write(
                        struct.pack("<II", 0x36424547, 8)
                        + struct.pack("<II", 0, 8 * 33)
                        + rng.randbytes(rng.randint(0, 100))
                    )
                else:  # random magic
                    writer.write(
                        struct.pack(
                            "<II",
                            rng.getrandbits(32),
                            rng.getrandbits(16),
                        )
                    )
                try:
                    writer.write_eof()
                except (OSError, NotImplementedError):
                    pass
                # the bridge must close (or error) this connection
                try:
                    data = await asyncio.wait_for(reader.read(-1), 5)
                except (
                    asyncio.IncompleteReadError,
                    ConnectionError,
                    asyncio.TimeoutError,
                ):
                    # a connection the bridge chose to keep open (e.g.
                    # a frame still waiting for its payload) is fine —
                    # the bridge's own read path is eof/length bounded;
                    # just abandon it
                    data = b""
                assert len(data) < (1 << 20)
                writer.close()
                try:
                    await asyncio.wait_for(writer.wait_closed(), 5)
                except (asyncio.TimeoutError, ConnectionError):
                    pass

            # bridge still serves a well-formed request afterwards
            from tests.test_edge_bridge import _read_hello

            reader, writer = await connect("tcp")
            await asyncio.wait_for(_read_hello(reader), 5)
            name, key = b"fz", b"alive"
            item = (
                struct.pack("<H", len(name)) + name
                + struct.pack("<H", len(key)) + key
                + struct.pack("<qqqBB", 1, 5, 60000, 0, 0)
            )
            writer.write(
                struct.pack("<II", 0x31424547, 1)
                + struct.pack("<I", len(item))
                + item
            )
            await writer.drain()
            magic, n = struct.unpack(
                "<II", await asyncio.wait_for(reader.readexactly(8), 10)
            )
            assert magic == 0x33424547 and n == 1
            writer.close()
        finally:
            await bridge.stop()

    asyncio.run(run())


pytestmark_edge = pytest.mark.skipif(
    not EDGE_BIN.exists(), reason="edge binary not built"
)


@pytestmark_edge
def test_randomized_mixed_shapes_through_router():
    """300 randomized batches of interleaved plain/GLOBAL/invalid items
    through the real edge against a 2-node ring (self + one reachable
    peer bridge): every item answers exactly once with the expected
    value and the expected path."""
    edge_http, peer_tcp = free_ports(2)
    sock_a = "/tmp/guber-router-shapes-a.sock"
    NODE_A, NODE_B = "10.97.1.1:81", "10.97.1.2:81"

    async def run():
        import os

        inst_a = CountingInstance(NODE_A, [NODE_A, NODE_B])
        inst_b = CountingInstance(NODE_B, [NODE_A, NODE_B])
        bridge_a = EdgeBridge(
            inst_a, sock_a,
            peer_bridges={NODE_B: f"127.0.0.1:{peer_tcp}"},
        )
        bridge_b = EdgeBridge(
            inst_b, "", tcp_address=f"127.0.0.1:{peer_tcp}"
        )
        try:
            os.unlink(sock_a)
        except FileNotFoundError:
            pass
        await bridge_a.start()
        await bridge_b.start()
        edge = subprocess.Popen(
            [str(EDGE_BIN), "--listen", str(edge_http),
             "--backend", sock_a, "--batch-wait-us", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        rng = random.Random(77)
        try:
            import socket as sl

            deadline = time.monotonic() + 10
            while True:
                if edge.poll() is not None:
                    pytest.fail(f"edge died:\n{edge.stdout.read()}")
                try:
                    sl.create_connection(
                        ("127.0.0.1", edge_http), timeout=1
                    ).close()
                    break
                except OSError:
                    assert time.monotonic() < deadline
                    await asyncio.sleep(0.05)
            # let the peer lane handshake so fast routing is active
            await asyncio.sleep(0.8)

            def call(batch):
                body = json.dumps({"requests": batch}).encode()
                return json.loads(
                    urllib.request.urlopen(
                        urllib.request.Request(
                            f"http://127.0.0.1:{edge_http}"
                            "/v1/GetRateLimits",
                            data=body,
                            headers={"Content-Type": "application/json"},
                        ),
                        timeout=20,
                    ).read()
                )

            for trial in range(300):
                n = rng.randint(1, 12)
                batch, kinds = [], []
                for i in range(n):
                    k = rng.choice(
                        ["plain", "plain", "plain", "global",
                         "nokey", "noname"]
                    )
                    kinds.append(k)
                    item = {
                        "name": "" if k == "noname" else "rs",
                        "uniqueKey": ""
                        if k == "nokey"
                        else f"t{trial}-{i}",
                        "hits": 1,
                        "limit": 9,
                        "duration": 60000,
                    }
                    if k == "global":
                        item["behavior"] = "GLOBAL"
                    batch.append(item)
                out = await asyncio.to_thread(call, batch)
                assert len(out["responses"]) == n
                for k, r in zip(kinds, out["responses"]):
                    if k == "nokey":
                        assert "unique_key" in r["error"], r
                    elif k == "noname":
                        assert "namespace" in r["error"], r
                    else:
                        assert r["error"] == "", (k, r)
                        assert r["remaining"] == "8", (k, r)
            # both paths actually exercised: fast items landed on both
            # nodes, and the string path served the GLOBAL/invalid mix
            assert inst_a.fast_items > 0 and inst_b.fast_items > 0, (
                inst_a.fast_items, inst_b.fast_items
            )
            assert inst_a.slow_items > 0
            assert inst_b.slow_items == 0  # forwards would need gRPC;
            # the string path stays on the primary with these fakes
        finally:
            edge.kill()
            await bridge_a.stop()
            await bridge_b.stop()

    asyncio.run(run())
