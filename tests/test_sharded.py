"""Mesh-sharded engine tests on the 8-virtual-device CPU mesh.

The mesh plays the role of the reference's peer cluster: each device owns a
key-space shard (the consistent-hash ring mapped onto the mesh axis), and
one psum combines per-shard decisions (reference peers.go forwarding
collapsed into a collective).
"""

import random

import jax
import numpy as np
import pytest

from gubernator_tpu.api.types import Algorithm, RateLimitReq, Status, SECOND
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.engine import TpuEngine
from gubernator_tpu.core.hashing import slot_hash_batch
from gubernator_tpu.core.oracle import get_rate_limit
from gubernator_tpu.core.store import StoreConfig, fingerprints
from gubernator_tpu.parallel.sharded import MeshEngine, owner_of_np

T0 = 1_700_000_000_000


@pytest.fixture(scope="module")
def mesh_engine():
    assert len(jax.devices()) == 8, "conftest should provide 8 cpu devices"
    return MeshEngine(
        StoreConfig(rows=4, slots=1 << 10), buckets=(64, 256)
    )


@pytest.fixture(autouse=True)
def _reset(mesh_engine):
    mesh_engine.reset()
    yield


def arrays_for(reqs):
    n = len(reqs)
    return dict(
        key_hash=slot_hash_batch([r.hash_key() for r in reqs]),
        hits=np.array([r.hits for r in reqs], np.int64),
        limit=np.array([r.limit for r in reqs], np.int64),
        duration=np.array([r.duration for r in reqs], np.int64),
        algo=np.array([int(r.algorithm) for r in reqs], np.int32),
        gnp=np.zeros(n, bool),
    )


def test_mesh_matches_oracle(mesh_engine):
    """Sharded decisions must equal the exact oracle, key by key."""
    rng = random.Random(7)
    cache = LRUCache()
    keys = [f"acct:{i}" for i in range(64)]
    now = T0
    for step in range(40):
        now += rng.choice([0, 3, 17, 120])
        batch_keys = rng.sample(keys, rng.randint(1, 32))
        reqs = [
            RateLimitReq(
                name="mesh",
                unique_key=k,
                hits=rng.choice([0, 1, 1, 2, 5]),
                limit=rng.choice([2, 5, 10]),
                duration=rng.choice([50, 1000]),
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
            )
            for k in batch_keys
        ]
        a = arrays_for(reqs)
        status, limit, remaining, reset = mesh_engine.decide_arrays(
            now=now, **a
        )
        for i, r in enumerate(reqs):
            want = get_rate_limit(cache, r, now=now)
            got = (status[i], limit[i], remaining[i], reset[i])
            expect = (
                int(want.status), want.limit, want.remaining, want.reset_time
            )
            assert got == expect, f"step={step} i={i} req={r}"


def test_keys_spread_across_shards(mesh_engine):
    hashes = slot_hash_batch([f"spread:{i}" for i in range(512)])
    owners = owner_of_np(hashes, mesh_engine.n)
    counts = np.bincount(owners, minlength=8)
    assert (counts > 20).all(), counts  # roughly uniform ownership


def test_batch_is_sharded_not_replicated(mesh_engine):
    """The scaling property: each chip's sub-batch holds only the rows it
    owns (~B/n), not the full batch — n chips do ~B work total, so
    aggregate decisions/s grows with the mesh instead of replicating the
    full batch on every chip."""
    from gubernator_tpu.parallel.sharded import pad_request_sharded

    B = 256
    hashes = slot_hash_batch([f"scale:{i}" for i in range(B)])
    req, order, take_idx = pad_request_sharded(
        mesh_engine.buckets,
        mesh_engine.config.slots,
        mesh_engine.n,
        hashes,
        np.ones(B, np.int64),
        np.full(B, 10, np.int64),
        np.full(B, 1000, np.int64),
        np.zeros(B, np.int32),
        np.zeros(B, bool),
    )
    n_shards, B_sub = req.key_hash.shape
    assert n_shards == mesh_engine.n
    # per-chip batch is ~B/n padded to a bucket, far below the full B
    counts = np.bincount(owner_of_np(hashes, mesh_engine.n), minlength=8)
    assert B_sub == 64 and B_sub >= counts.max(), (B_sub, counts)
    # every valid row sits on the shard that owns its key
    for s in range(n_shards):
        v = req.valid[s]
        assert v.sum() == counts[s]
        assert (owner_of_np(req.key_hash[s][v], mesh_engine.n) == s).all()
    # round-trip: order/take_idx reassemble the original key order
    flat = req.key_hash.reshape(-1)
    back = np.empty(B, np.uint64)
    back[order] = flat[take_idx]
    assert (back == hashes).all()


def test_skewed_shard_overflows_ladder_gracefully(mesh_engine):
    """A batch larger than the ladder's top rung, worst-case skewed
    (every key below owned by shard 0), must extend the rung progression
    dynamically and still match the oracle — not raise. The serving tier
    never sends such a batch (the batcher caps it at the ladder top), but
    library callers can, and per-shard counts additionally depend on the
    slot-hash backend (XXH64 native vs blake2b fallback), so the engine
    cannot treat the ladder as a hard bound."""
    top = max(mesh_engine.sub_buckets)
    n = top + 40
    pool = np.random.default_rng(3).integers(
        1, 1 << 63, size=16 * n, dtype=np.uint64
    )
    mine = pool[owner_of_np(pool, mesh_engine.n) == 0]
    assert mine.shape[0] >= n
    kh = mine[:n]
    cache = LRUCache()
    status, _, remaining, _ = mesh_engine.decide_arrays(
        kh,
        np.ones(n, np.int64),
        np.full(n, 10, np.int64),
        np.full(n, 60_000, np.int64),
        np.zeros(n, np.int32),
        np.zeros(n, bool),
        T0,
    )
    r = RateLimitReq(name="skew", unique_key="k", hits=1, limit=10,
                     duration=60_000)
    want = get_rate_limit(cache, r, now=T0)
    assert (status == int(want.status)).all()
    assert (remaining == want.remaining).all()
    # the paired GLOBAL calls must accept the same oversized batch
    mesh_engine.sync_globals(
        kh, np.full(n, 10, np.int64), np.full(n, 60_000, np.int64), now=T0
    )
    mesh_engine.update_globals(
        kh,
        np.full(n, 10, np.int64),
        np.full(n, 9, np.int64),
        np.full(n, T0 + 60_000, np.int64),
        np.zeros(n, bool),
        now=T0,
    )


def test_sync_globals_installs_replicas_on_all_shards(mesh_engine):
    reqs = [
        RateLimitReq(
            name="glob", unique_key="account:42", hits=1, limit=5,
            duration=3 * SECOND,
        )
    ]
    a = arrays_for(reqs)
    # two hits against the owner shard
    mesh_engine.decide_arrays(now=T0, **a)
    mesh_engine.decide_arrays(now=T0, **a)

    mesh_engine.sync_globals(
        a["key_hash"], a["limit"], a["duration"], now=T0
    )

    # the key's fingerprint must now exist on every shard (owner holds the
    # authoritative entry; others hold replicas of the broadcast status)
    kh = a["key_hash"]
    fp = int(np.asarray(jax.device_get(fingerprints(kh)))[0])
    tags = np.asarray(jax.device_get(mesh_engine.store.tag))  # [n, rows, slots]
    rem = np.asarray(jax.device_get(mesh_engine.store.remaining))
    per_shard = (tags == fp).any(axis=(1, 2))
    assert per_shard.all(), per_shard
    # every replica carries the authoritative remaining (5 - 2 hits = 3)
    for s in range(mesh_engine.n):
        vals = rem[s][tags[s] == fp]
        assert (vals == 3).all(), (s, vals)


def test_sync_globals_leaky_preserves_owner_state(mesh_engine):
    # Regression: a sync peek with the wrong algorithm would take the
    # mismatch-recreate path and refill the owner's depleted leaky bucket.
    reqs = [
        RateLimitReq(
            name="glk", unique_key="u", hits=5, limit=5, duration=5000,
            algorithm=Algorithm.LEAKY_BUCKET,
        )
    ]
    a = arrays_for(reqs)
    mesh_engine.decide_arrays(now=T0, **a)  # drain to 0
    mesh_engine.sync_globals(
        a["key_hash"], a["limit"], a["duration"], now=T0,
        algo=np.full(1, 1, np.int32),
    )
    # bucket still empty after the sync
    status, _, remaining, _ = mesh_engine.decide_arrays(now=T0, **a)
    assert (int(status[0]), int(remaining[0])) == (int(Status.OVER_LIMIT), 0)


def test_mesh_grouped_subbatches_match_oracle(mesh_engine):
    """Duplicate-heavy batch large enough that per-shard sub-batches use
    a COMPACT group rung (G_sub < B_sub) — the mesh sibling of the
    engine's unique-key store-I/O compaction — must still match the
    exact oracle row for row."""
    rng = random.Random(11)
    cache = LRUCache()
    keys = [f"grp:{i}" for i in range(120)]
    now = T0
    for step in range(3):
        now += 20
        batch_keys = [rng.choice(keys) for _ in range(1600)]
        reqs = [
            RateLimitReq(
                name="mesh-g", unique_key=k, hits=1, limit=40,
                duration=60_000,
            )
            for k in batch_keys
        ]
        a = arrays_for(reqs)
        status, limit, remaining, reset = mesh_engine.decide_arrays(
            now=now, **a
        )
        for i, r in enumerate(reqs):
            want = get_rate_limit(cache, r, now=now)
            got = (status[i], limit[i], remaining[i], reset[i])
            expect = (
                int(want.status), want.limit, want.remaining, want.reset_time
            )
            assert got == expect, f"step={step} i={i} req={r}"


def test_mesh_duplicate_keys_one_batch(mesh_engine):
    reqs = [
        RateLimitReq(
            name="dup", unique_key="k", hits=1, limit=3, duration=SECOND
        )
        for _ in range(5)
    ]
    a = arrays_for(reqs)
    status, _, remaining, _ = mesh_engine.decide_arrays(now=T0, **a)
    assert list(remaining) == [2, 1, 0, 0, 0]
    assert list(status) == [0, 0, 0, 1, 1]


def test_mesh_submit_wait_pipelined(mesh_engine):
    """Two decide batches in flight (the batcher's pipelining bound):
    submits strictly ordered, waits resolve each batch correctly, and the
    store threads through — batch 2 sees batch 1's charges."""
    reqs = [
        RateLimitReq(
            name="pipe", unique_key=f"k{i % 7}", hits=1, limit=4,
            duration=60_000,
        )
        for i in range(21)
    ]
    a = arrays_for(reqs)
    h1 = mesh_engine.decide_submit(now=T0, **a)
    h2 = mesh_engine.decide_submit(now=T0, **a)  # before h1's wait
    s1, _, r1, _ = mesh_engine.decide_wait(h1)
    s2, _, r2, _ = mesh_engine.decide_wait(h2)
    # 7 keys x 3 dups per batch, limit 4: batch 1 ends remaining=1 per
    # key; batch 2 charges once more then hits the limit
    for k in range(7):
        rows = [i for i in range(21) if i % 7 == k]
        assert [int(r1[i]) for i in rows] == [3, 2, 1]
        assert [int(s1[i]) for i in rows] == [0, 0, 0]
        assert [int(r2[i]) for i in rows] == [0, 0, 0]
        assert [int(s2[i]) for i in rows] == [0, 1, 1]


def test_mesh_wait_uses_submit_time_epoch(mesh_engine):
    """A rebase between submit and wait must not skew the in-flight
    batch's reset_time: the handle carries its submit-time epoch (same
    contract as TpuEngine.decide_submit)."""
    from gubernator_tpu.core.store import REBASE_AT

    reqs = [
        RateLimitReq(
            name="epoch", unique_key="x", hits=1, limit=5,
            duration=60_000,
        )
    ]
    a = arrays_for(reqs)
    h1 = mesh_engine.decide_submit(now=T0, **a)
    # advance the clock past the rebase threshold mid-flight
    h2 = mesh_engine.decide_submit(now=T0 + REBASE_AT + 1000, **a)
    _, _, _, reset1 = mesh_engine.decide_wait(h1)
    _, _, _, reset2 = mesh_engine.decide_wait(h2)
    # batch 1 converts against ITS epoch even though a rebase happened
    # before its wait
    assert int(reset1[0]) == T0 + 60_000
    # the 12-day jump rebased batch 1's window to expired, so batch 2
    # recreates it at the new now (state-loss-on-jump contract)
    assert int(reset2[0]) == T0 + REBASE_AT + 1000 + 60_000


# -- hierarchical (ICI -> DCN) mesh, BASELINE config 5 ---------------------


def test_hierarchical_mesh_matches_flat():
    """A forced 2-D ("host", "chip") mesh must produce decision-for-
    decision the same results as the flat 8-shard mesh: placement is
    the flattened host-major index, so only the reduction STRUCTURE
    changes (staged psum), never the answers."""
    flat = MeshEngine(StoreConfig(rows=4, slots=1 << 10), buckets=(64,))
    hier = MeshEngine(
        StoreConfig(rows=4, slots=1 << 10), buckets=(64,),
        mesh_shape=(4, 2),
    )
    assert hier.axes == ("host", "chip")
    assert dict(hier.mesh.shape) == {"host": 4, "chip": 2}

    rng = random.Random(11)
    keys = [f"hier:{i}" for i in range(48)]
    now = T0
    for step in range(12):
        now += rng.choice([0, 5, 250])
        batch = rng.sample(keys, rng.randint(1, 32))
        a = dict(
            key_hash=slot_hash_batch(batch),
            hits=np.array(
                [rng.randint(0, 3) for _ in batch], np.int64
            ),
            limit=np.full(len(batch), 5, np.int64),
            duration=np.full(len(batch), 60_000, np.int64),
            algo=np.array(
                [rng.randint(0, 1) for _ in batch], np.int32
            ),
            gnp=np.zeros(len(batch), bool),
        )
        rf = flat.decide_arrays(now=now, **a)
        rh = hier.decide_arrays(now=now, **a)
        for f, h in zip(rf, rh):
            np.testing.assert_array_equal(np.asarray(f), np.asarray(h))
        if step == 6:
            kh = a["key_hash"]
            flat.sync_globals(
                kh, a["limit"], a["duration"], now, a["algo"]
            )
            hier.sync_globals(
                kh, a["limit"], a["duration"], now, a["algo"]
            )


def test_hierarchical_sync_stages_collectives():
    """The compiled GLOBAL-sync step on the 2-D mesh must contain the
    two-level reduction of BASELINE config 5: an intra-host all-reduce
    (replica groups of chip-axis size, the ICI legs) AND an inter-host
    all-reduce (groups spanning hosts, the DCN legs) — while the flat
    mesh compiles a single all-reduce over all 8 shards."""
    import re

    def sync_hlo(eng):
        B = 64
        s = jax.ShapeDtypeStruct
        # (store, key_hash, hits, limit, duration, algo, valid, now) —
        # hits is the r14 in-mesh GLOBAL aggregation leg (zeros = the
        # classic peek-only gossip step)
        return eng._sync.lower(
            eng.store, s((B,), np.uint64), s((B,), np.int32),
            s((B,), np.int32), s((B,), np.int32), s((B,), np.int32),
            s((B,), bool), s((), np.int32),
        ).as_text()

    def groups(txt):
        return {
            m.replace(" ", "")
            for m in re.findall(
                r'all_reduce"?[^\n]*?dense<(\[\[[^>]*\]\])>', txt
            )
        }

    flat = MeshEngine(StoreConfig(rows=4, slots=256), buckets=(64,))
    g_flat = groups(sync_hlo(flat))
    assert g_flat == {"[[0,1,2,3,4,5,6,7]]"}, g_flat

    hier = MeshEngine(
        StoreConfig(rows=4, slots=256), buckets=(64,), mesh_shape=(4, 2)
    )
    g_hier = groups(sync_hlo(hier))
    # intra-host (chip) stage: 4 groups of 2; inter-host stage: 2
    # groups of 4 — and no flat 8-wide all-reduce anywhere
    assert "[[0,1],[2,3],[4,5],[6,7]]" in g_hier, g_hier
    assert "[[0,2,4,6],[1,3,5,7]]" in g_hier, g_hier
    assert "[[0,1,2,3,4,5,6,7]]" not in g_hier, g_hier
