"""r20 differential fuzz: mesh-native GLOBAL == RPC-gossip GLOBAL.

The collective flush (PartitionedEngine.apply_global_hits: owner charge
+ psum replicate + replica install in ONE device program) must produce
byte-identical decisions to the RPC gossip cycle it replaces — the
owner's decide charge, which is exactly what the gossip receive door
(get_peer_rate_limits -> decide_local -> decide) runs. Legs pinned
here, all under the r10 fake clock:

- flat engine collective vs the flat RPC reference (degenerate mesh),
- 8-device mesh collective vs the same flat RPC reference,
- serve-level mixed ring: the GlobalManager flush through a REAL
  Instance + MeshBackend (batcher-serialized apply_global_hits_reqs)
  equals the reference backend that received the same hits over the
  gossip door.

The 2-process multihost engine leg of the same pin lives in
tests/_multihost_runner.py (the "ghits" exercise) — lockstep follower
processes can't run under plain pytest. The fake-peer path-selection
unit pins (self short-circuit, GUBER_GLOBAL_MESH=0 escape) live in
tests/test_global_mgr.py.
"""

import asyncio

import numpy as np

import gubernator_tpu.core  # noqa: F401  (x64)
from gubernator_tpu.api.types import Behavior, PeerInfo, RateLimitReq
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.parallel.sharded import MeshEngine, TpuEngine, owner_of_np
from gubernator_tpu.serve.backends import MeshBackend, TpuBackend
from gubernator_tpu.serve.config import BehaviorConfig, ServerConfig
from gubernator_tpu.serve.instance import Instance

T0 = 1_700_000_000_000
ADDR = "127.0.0.1:7976"


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self) -> int:
        return self.t


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


def _arrays_equal(a, b, ctx=""):
    for name, x, y in zip(("status", "limit", "remaining", "reset"), a, b):
        np.testing.assert_array_equal(
            np.asarray(x, np.int64),
            np.asarray(y, np.int64),
            err_msg=f"{ctx}: {name} diverged",
        )


def test_fuzz_collective_flush_equals_rpc_gossip_charge():
    """Seeded fuzz over mixed algorithms / limits / durations / clock
    jumps: every flush's post-charge response and every interleaved
    authoritative decision stays byte-identical between the RPC
    reference (decide charge on a flat engine) and the collective
    apply on BOTH the flat and the 8-device mesh engine."""
    cfg = StoreConfig(rows=4, slots=1 << 10)
    rpc = TpuEngine(cfg, buckets=(64,))  # reference: gossip-door charge
    col_flat = TpuEngine(cfg, buckets=(64,))
    col_mesh = MeshEngine(cfg, buckets=(64,))

    rng = np.random.default_rng(0x6E0B)
    n_keys = 48
    kh = rng.integers(1, 2**63, n_keys, np.int64).astype(np.uint64)
    # keys must spread over shards or the mesh psum degenerates
    assert len(set(owner_of_np(kh, col_mesh.n).tolist())) >= 4
    lim = rng.integers(3, 40, n_keys).astype(np.int64)
    dur = rng.integers(1, 60, n_keys).astype(np.int64) * 10_000
    algo = rng.integers(0, 2, n_keys).astype(np.int32)

    now = T0
    for rnd in range(8):
        now += int(rng.integers(0, 20_000))
        pick = np.flatnonzero(rng.random(n_keys) < 0.6)
        if pick.size == 0:
            continue
        hits = rng.integers(1, 5, pick.size).astype(np.int64)
        k, l, d, a = kh[pick], lim[pick], dur[pick], algo[pick]
        # RPC reference: the owner's decide charge, exactly what the
        # gossip receive door runs for a forwarded hit chunk
        rr = rpc.decide_arrays(
            k, hits, l, d, a, np.zeros(pick.size, bool), now
        )
        _arrays_equal(
            rr, col_flat.apply_global_hits(k, hits, l, d, now, algo=a),
            f"round {rnd} flat",
        )
        _arrays_equal(
            rr, col_mesh.apply_global_hits(k, hits, l, d, now, algo=a),
            f"round {rnd} mesh",
        )
        # interleaved authoritative decisions on ALL keys (charges on
        # both sides identically, so the fuzz keeps compounding state)
        if rnd % 3 == 2:
            now += 1
            one = np.ones(n_keys, np.int64)
            gnp = np.zeros(n_keys, bool)
            dr = rpc.decide_arrays(kh, one, lim, dur, algo, gnp, now)
            _arrays_equal(
                dr,
                col_flat.decide_arrays(kh, one, lim, dur, algo, gnp, now),
                f"round {rnd} flat decide",
            )
            _arrays_equal(
                dr,
                col_mesh.decide_arrays(kh, one, lim, dur, algo, gnp, now),
                f"round {rnd} mesh decide",
            )
    # replica install leg: non-owner (gnp) peeks answer from the
    # replicas the collective installed — identical to the flat
    # engines' owner-state reads at the same instant
    now += 1
    zero = np.zeros(n_keys, np.int64)
    gnp = np.ones(n_keys, bool)
    pr = rpc.decide_arrays(kh, zero, lim, dur, algo, gnp, now)
    _arrays_equal(
        pr, col_flat.decide_arrays(kh, zero, lim, dur, algo, gnp, now),
        "final flat gnp peek",
    )
    _arrays_equal(
        pr, col_mesh.decide_arrays(kh, zero, lim, dur, algo, gnp, now),
        "final mesh gnp peek",
    )


def test_serve_level_mixed_ring_flush_equals_gossip_door(monkeypatch):
    """End-to-end through the serving stack: a ring with one off-mesh
    peer — self-owned GLOBAL hits flush through the REAL instance's
    local apply (batcher-serialized apply_global_hits_reqs collective),
    off-mesh keys go RPC to the fake peer — and the post-flush state
    equals a reference backend that received the same self-owned hits
    over the gossip door (decide)."""
    import jax

    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    cfg = StoreConfig(rows=4, slots=1 << 10)
    ref = TpuBackend(cfg, buckets=(64,))
    backend = MeshBackend(cfg, devices=jax.devices(), buckets=(64,))
    assert backend.apply_global_hits_reqs is not None
    conf = ServerConfig(
        grpc_address=ADDR, advertise_address=ADDR, backend="mesh",
        # windows absurdly long: only explicit drain() flushes
        behaviors=BehaviorConfig(global_sync_wait=600.0), sketch=False,
    )

    class OffMeshPeer:
        host = "10.9.9.9:7975"
        is_owner = False
        hit_batches: list = []

        async def get_peer_rate_limits(self, reqs):
            self.hit_batches.append(list(reqs))
            return []

        async def update_peer_globals(self, updates):
            pass

    off = OffMeshPeer()

    async def run():
        inst = Instance(conf, backend)
        inst.start()
        await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
        self_peer = inst.get_peer("anything")
        assert self_peer.is_owner

        # mixed ring: route a slice of keys to the off-mesh peer
        def route(key):
            return off if key.split("_", 1)[1].startswith("r") else self_peer

        monkeypatch.setattr(inst, "get_peer", route)
        try:
            mine = [
                RateLimitReq(
                    name="gd", unique_key=f"m{i}", hits=(i % 3) + 1,
                    limit=10, duration=60_000, behavior=Behavior.GLOBAL,
                )
                for i in range(24)
            ]
            remote = [
                RateLimitReq(
                    name="gd", unique_key=f"r{i}", hits=1, limit=10,
                    duration=60_000, behavior=Behavior.GLOBAL,
                )
                for i in range(4)
            ]
            for r in mine + remote:
                inst.global_mgr.queue_hit(r)
            await inst.global_mgr.drain()
            # off-mesh keys went over gossip RPC, self keys did not
            (sent,) = off.hit_batches
            assert {r.unique_key for r in sent} == {
                r.unique_key for r in remote
            }
            # reference: the same self-owned chunk arriving over the
            # gossip door is just a decide on the owner
            ref.decide(mine, [False] * len(mine), now=clock())
            clock.t += 5
            peek = [
                RateLimitReq(
                    name="gd", unique_key=f"m{i}", hits=0, limit=10,
                    duration=60_000,
                )
                for i in range(24)
            ]
            a = ref.decide(peek, [False] * len(peek), now=clock())
            b = backend.decide(peek, [False] * len(peek), now=clock())
            for x, y in zip(a, b):
                assert (x.status, x.limit, x.remaining, x.reset_time) == (
                    y.status, y.limit, y.remaining, y.reset_time
                )
            # the local apply queues the owner broadcast for the ring
            # (drain() above already consumed the first batch, so pin
            # the hook directly)
            await inst.apply_global_hits_local(mine[:2])
            assert set(inst.global_mgr._updates) == {
                r.hash_key() for r in mine[:2]
            }, "local apply did not queue the owner broadcast"
        finally:
            await inst.stop()

    asyncio.run(asyncio.wait_for(run(), timeout=60))
