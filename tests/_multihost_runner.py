"""Two-process multi-host mesh exercise (spawned by test_multihost.py).

Run as: python tests/_multihost_runner.py <role> <coordinator> <step_port>
Role "leader" drives rate-limit traffic over a 2-process global mesh and
asserts the decisions; role "follower" runs the lockstep loop. Leader
prints LEADER-OK on success. Roles "leader-mismatch"/"follower-mismatch"
exercise the connect-time config handshake: the follower is constructed
with a different bucket ladder and both sides must fail loudly with the
mismatch diagnostic (no hang, no silent shape divergence).
"""

import sys


def main():
    role, coordinator, step_port = sys.argv[1], sys.argv[2], sys.argv[3]

    import jax

    jax.config.update("jax_platforms", "cpu")

    from gubernator_tpu.parallel.multihost import (
        MultiHostMeshEngine,
        initialize_distributed,
    )
    from gubernator_tpu.core.store import StoreConfig
    import numpy as np

    pid = 0 if role.startswith("leader") else 1
    initialize_distributed(coordinator, num_processes=2, process_id=pid)
    assert len(jax.devices()) == 2, jax.devices()

    cfg = StoreConfig(rows=16, slots=1 << 8)
    T0 = 1_700_000_000_000

    if role == "follower-mismatch":
        eng = MultiHostMeshEngine(cfg, buckets=(32,))  # leader has (16,)
        try:
            eng.follower_loop(f"127.0.0.1:{step_port}")
        except RuntimeError as e:
            assert "config mismatch" in str(e), e
            print("FOLLOWER-MISMATCH-OK", flush=True)
            return
        raise SystemExit("follower accepted a mismatched leader config")

    if role == "leader-mismatch":
        try:
            MultiHostMeshEngine(
                cfg, followers=[f"127.0.0.1:{step_port}"], buckets=(16,)
            )
        except RuntimeError as e:
            assert "config mismatch" in str(e), e
            print("LEADER-MISMATCH-OK", flush=True)
            return
        raise SystemExit("leader handshake accepted a mismatched follower")

    if role == "follower":
        eng = MultiHostMeshEngine(cfg, buckets=(16,))
        eng.follower_loop(f"127.0.0.1:{step_port}")
        print("FOLLOWER-OK", flush=True)
        return

    eng = MultiHostMeshEngine(
        cfg, followers=[f"127.0.0.1:{step_port}"], buckets=(16,)
    )

    from gubernator_tpu.core.hashing import slot_hash_batch
    from gubernator_tpu.parallel.sharded import owner_of_np

    # enough keys that both shards (one device per process) own some
    keys = [f"mh:{i}" for i in range(12)]
    kh = slot_hash_batch(keys)
    owners = owner_of_np(kh, 2)
    assert set(owners.tolist()) == {0, 1}, "keys must span both hosts"

    ones = np.ones(len(keys), np.int64)
    limit = ones * 2
    dur = ones * 60_000
    algo = np.zeros(len(keys), np.int32)
    gnp = np.zeros(len(keys), bool)

    # two charges then OVER, across both shards, via the global-mesh psum
    s1, _, r1, _ = eng.decide_arrays(kh, ones, limit, dur, algo, gnp, T0)
    assert (s1 == 0).all() and (r1 == 1).all(), (s1, r1)
    s2, _, r2, _ = eng.decide_arrays(kh, ones, limit, dur, algo, gnp, T0 + 1)
    assert (s2 == 0).all() and (r2 == 0).all(), (s2, r2)
    s3, _, r3, _ = eng.decide_arrays(kh, ones, limit, dur, algo, gnp, T0 + 2)
    assert (s3 == 1).all() and (r3 == 0).all(), (s3, r3)

    # GLOBAL gossip collective: owner peek + broadcast + replica install
    eng.sync_globals(kh, limit, dur, T0 + 3)
    # replica reads answer from installed state everywhere
    s4, _, r4, _ = eng.decide_arrays(
        kh, np.zeros(len(keys), np.int64), limit, dur, algo,
        np.ones(len(keys), bool), T0 + 4,
    )
    assert (s4 == 1).all(), s4  # all shards report the OVER status

    # broadcast-install path (UpdatePeerGlobals receive side)
    eng.update_globals(
        kh, ones * 9, ones * 7, ones * (T0 + 60_000),
        np.zeros(len(keys), bool), now=T0 + 5,
    )
    s5, l5, r5, _ = eng.decide_arrays(
        kh, np.zeros(len(keys), np.int64), ones * 9, dur, algo,
        np.ones(len(keys), bool), T0 + 6,
    )
    assert (r5 == 7).all() and (l5 == 9).all(), (l5, r5)

    eng.close()
    print("LEADER-OK", flush=True)


if __name__ == "__main__":
    main()
