"""Multi-process multi-host mesh exercise (spawned by test_multihost.py).

Run as:
  python tests/_multihost_runner.py <role> <coordinator> <step_ports> \
      <process_id> <num_processes>

`step_ports` is comma-separated: the leader connects to one port per
follower; a follower listens on its own (single) entry. Devices per
process come from XLA_FLAGS --xla_force_host_platform_device_count in the
environment (1 if unset), so one runner covers 2x1, 2x4, and 4x2
topologies. Role "leader" drives rate-limit traffic over the global mesh
and asserts decisions, ownership spread across every shard, gossip
convergence, and the process-major device ordering the scaling model
relies on (parallel/multihost.py module docstring); it prints LEADER-OK
plus a `TOPO shards=<n> b_sub=<B>` work line for the cross-topology
flatness check. Roles "leader-mismatch"/"follower-mismatch" exercise the
connect-time config handshake.
"""

import sys


def main():
    role, coordinator, step_ports, pid_s, nprocs_s = sys.argv[1:6]
    pid, nprocs = int(pid_s), int(nprocs_s)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from gubernator_tpu.parallel.multihost import (
        MultiHostMeshEngine,
        initialize_distributed,
    )
    from gubernator_tpu.core.store import StoreConfig
    import numpy as np

    initialize_distributed(coordinator, num_processes=nprocs, process_id=pid)

    from gubernator_tpu.core.sketches import SketchConfig

    cfg = StoreConfig(rows=16, slots=1 << 8)
    # r20: the lockstep roles carry the count-min cold tier so every
    # decide dispatch exercises the two-tier collective program and the
    # leader can drive promote/ghits across the process boundary
    SK = SketchConfig(rows=2, width=1 << 10)
    T0 = 1_700_000_000_000

    if role == "follower-mismatch":
        eng = MultiHostMeshEngine(cfg, buckets=(32,))  # leader has (16,)
        try:
            eng.follower_loop(f"127.0.0.1:{step_ports}")
        except RuntimeError as e:
            assert "config mismatch" in str(e), e
            print("FOLLOWER-MISMATCH-OK", flush=True)
            return
        raise SystemExit("follower accepted a mismatched leader config")

    if role == "leader-mismatch":
        try:
            MultiHostMeshEngine(
                cfg,
                followers=[f"127.0.0.1:{p}" for p in step_ports.split(",")],
                buckets=(16,),
            )
        except RuntimeError as e:
            assert "config mismatch" in str(e), e
            print("LEADER-MISMATCH-OK", flush=True)
            return
        raise SystemExit("leader handshake accepted a mismatched follower")

    # the scaling-model claim (multihost.py docstring): jax device order
    # is process-major, so a reduction's intra-host hops ride ICI before
    # the host-level combine crosses DCN. Assert it in EVERY process.
    devs = jax.devices()
    proc_of = [d.process_index for d in devs]
    assert proc_of == sorted(proc_of), f"not process-major: {proc_of}"
    per = len(devs) // nprocs
    for p in range(nprocs):
        block = proc_of[p * per : (p + 1) * per]
        assert block == [p] * per, f"process {p} devices not contiguous: {proc_of}"

    if role == "follower":
        eng = MultiHostMeshEngine(cfg, buckets=(16,), sketch=SK)
        eng.follower_loop(f"127.0.0.1:{step_ports}")
        print("FOLLOWER-OK", flush=True)
        return

    eng = MultiHostMeshEngine(
        cfg,
        followers=[f"127.0.0.1:{p}" for p in step_ports.split(",")],
        buckets=(16,),
        sketch=SK,
    )
    n_shards = eng.n
    assert n_shards == len(devs), (n_shards, devs)

    # r5: a multi-process mesh must be the 2-D ("host", "chip") form so
    # the GLOBAL-sync reduction stages ICI-within-host before DCN
    # (BASELINE config 5 "hierarchical psum"); structure is asserted
    # from the compiled module in tests/test_sharded.py
    assert eng.inner.axes == ("host", "chip"), eng.inner.axes
    assert dict(eng.inner.mesh.shape) == {"host": nprocs, "chip": per}, (
        eng.inner.mesh.shape
    )

    from gubernator_tpu.core.hashing import slot_hash_batch
    from gubernator_tpu.parallel.sharded import owner_of_np, pad_request_sharded

    # enough keys that EVERY shard owns some
    keys = [f"mh:{i}" for i in range(16 * n_shards)]
    kh = slot_hash_batch(keys)
    owners = owner_of_np(kh, n_shards)
    assert set(owners.tolist()) == set(range(n_shards)), (
        f"keys must span all {n_shards} shards: {sorted(set(owners.tolist()))}"
    )

    n = len(keys)
    ones = np.ones(n, np.int64)
    limit = ones * 2
    dur = ones * 60_000
    algo = np.zeros(n, np.int32)
    gnp = np.zeros(n, bool)

    # cross-topology work line: padded per-shard sub-batch for this batch
    req, _o, _t, _g = pad_request_sharded(
        eng.sub_buckets, cfg.slots, n_shards, kh, ones, limit, dur, algo,
        gnp, with_groups=True,
    )
    print(f"TOPO shards={n_shards} b_sub={req.key_hash.shape[1]}", flush=True)

    # two charges then OVER, across every shard, via the global-mesh psum
    s1, _, r1, _ = eng.decide_arrays(kh, ones, limit, dur, algo, gnp, T0)
    assert (s1 == 0).all() and (r1 == 1).all(), (s1, r1)
    s2, _, r2, _ = eng.decide_arrays(kh, ones, limit, dur, algo, gnp, T0 + 1)
    assert (s2 == 0).all() and (r2 == 0).all(), (s2, r2)
    s3, _, r3, _ = eng.decide_arrays(kh, ones, limit, dur, algo, gnp, T0 + 2)
    assert (s3 == 1).all() and (r3 == 0).all(), (s3, r3)

    # GLOBAL gossip collective: owner peek + broadcast + replica install
    eng.sync_globals(kh, limit, dur, T0 + 3)
    s4, _, r4, _ = eng.decide_arrays(
        kh, np.zeros(n, np.int64), limit, dur, algo,
        np.ones(n, bool), T0 + 4,
    )
    assert (s4 == 1).all(), s4  # every shard reports the OVER status

    # broadcast-install path (UpdatePeerGlobals receive side)
    eng.update_globals(
        kh, ones * 9, ones * 7, ones * (T0 + 60_000),
        np.zeros(n, bool), now=T0 + 5,
    )
    s5, l5, r5, _ = eng.decide_arrays(
        kh, np.zeros(n, np.int64), ones * 9, dur, algo,
        np.ones(n, bool), T0 + 6,
    )
    assert (r5 == 7).all() and (l5 == 9).all(), (l5, r5)

    # pipelined split across hosts (r4): TWO submits in flight before
    # either wait — followers dispatch-and-move-on, the leader fetches
    # later; both batches' collectives and store threading must line up
    kh2 = kh * np.uint64(3) | np.uint64(1)
    h1 = eng.decide_submit(
        kh2, ones, ones * 2, dur, algo, gnp, T0 + 7
    )
    h2 = eng.decide_submit(
        kh2, ones, ones * 2, dur, algo, gnp, T0 + 8
    )
    s6, _, r6, _ = eng.decide_wait(h1)
    s7, _, r7, _ = eng.decide_wait(h2)
    assert (s6 == 0).all() and (r6 == 1).all(), (s6, r6)
    assert (s7 == 0).all() and (r7 == 0).all(), (s7, r7)

    # -- r20: mesh-native GLOBAL hits, differential vs the RPC path ----------
    # The collective flush (one lockstep ghits step across processes)
    # must be byte-identical to the gossip door's decide charge; a flat
    # single-device reference engine on the leader plays the RPC side.
    from gubernator_tpu.parallel.sharded import TpuEngine

    # tall ladder: the reference takes whole batches flat (decisions
    # are rung-independent; only the mesh side must match the lockstep
    # ladder)
    ref = TpuEngine(cfg, buckets=(2048,), sketch=SK)
    # sketch windows are quantized epoch-relative (engine-ms //
    # duration), so promote reset times only match when both engines
    # pinned the same epoch; eng pinned at its first decide (T0)
    ref._engine_now(T0)
    kh3 = kh * np.uint64(5) | np.uint64(2)
    hits3 = (np.arange(n, dtype=np.int64) % 3) + 1
    lim3 = ones * 7
    for step in range(2):  # second flush compounds on the same windows
        rr = ref.decide_arrays(kh3, hits3, lim3, dur, algo, gnp, T0 + 20 + step)
        mm = eng.apply_global_hits(kh3, hits3, lim3, dur, T0 + 20 + step)
        for a, b in zip(rr, mm):
            np.testing.assert_array_equal(
                np.asarray(a, np.int64), np.asarray(b, np.int64)
            )
    # replica-install leg: gnp peeks answer from the windows the
    # collective installed on every shard, equal to the owner state
    rp = ref.decide_arrays(
        kh3, np.zeros(n, np.int64), lim3, dur, algo, np.ones(n, bool),
        T0 + 22,
    )
    mp = eng.decide_arrays(
        kh3, np.zeros(n, np.int64), lim3, dur, algo, np.ones(n, bool),
        T0 + 22,
    )
    for a, b in zip(rp, mp):
        np.testing.assert_array_equal(
            np.asarray(a, np.int64), np.asarray(b, np.int64)
        )
    print("GHITS-OK", flush=True)

    # -- r20: sketch tier on multihost — lockstep promote collective ---------
    # promote_from_sketch rides a `promote` broadcast: every process
    # issues the identical collective estimate + live-mask reads and
    # the conditional window install. Differential vs the flat engine.
    khp = (
        np.arange(1, 4 * n_shards + 1, dtype=np.uint64) << np.uint64(32)
    ) | np.uint64(7)
    np_ = khp.shape[0]
    limsP = np.full(np_, 5, np.int64)
    dursP = np.full(np_, 60_000, np.int64)
    mt = eng.promote_from_sketch(khp, limsP, dursP, T0 + 30)
    rt = ref.promote_from_sketch(khp, limsP, dursP, T0 + 30)
    for a, b in zip(mt, rt):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert mt[0].all(), "first promote should install every key"
    # installs landed mesh-wide: a second promote skips every key
    # (live exact entries are authoritative) — on BOTH engines
    mt2 = eng.promote_from_sketch(khp, limsP, dursP, T0 + 31)
    rt2 = ref.promote_from_sketch(khp, limsP, dursP, T0 + 31)
    assert not mt2[0].any() and not rt2[0].any(), (mt2[0], rt2[0])
    # promoted keys now decide exactly, byte-identical to the reference
    onesP = np.ones(np_, np.int64)
    sa = ref.decide_arrays(
        khp, onesP, limsP, dursP, np.zeros(np_, np.int32),
        np.zeros(np_, bool), T0 + 32,
    )
    sb = eng.decide_arrays(
        khp, onesP, limsP, dursP, np.zeros(np_, np.int32),
        np.zeros(np_, bool), T0 + 32,
    )
    for a, b in zip(sa, sb):
        np.testing.assert_array_equal(
            np.asarray(a, np.int64), np.asarray(b, np.int64)
        )
    print("SKETCH-OK", flush=True)

    # -- r21: window-ring serving on multihost — sliding + GCRA --------------
    # One sliding and one GCRA key whose bucket (on its owner shard) is
    # way-saturated with immortal fillers: every create drops, so each
    # decide is served by the per-shard ring through the SAME lockstep
    # collective program, and the responses must be bit-exact against
    # the host twins (algorithms.sketch_sliding_budget /
    # sketch_gcra_budget) fed by the host-tracked charge log — the ring
    # cells are written by these two keys only, so estimate == charges.
    from gubernator_tpu.core.algorithms import (
        gcra_params,
        sketch_gcra_budget,
        sketch_sliding_budget,
    )
    from gubernator_tpu.core.hashing import mix64
    from gubernator_tpu.core.store import _BUCKET_SALT

    def shard_bucket(arr):
        o = owner_of_np(arr, n_shards)
        b = mix64(arr ^ _BUCKET_SALT) & np.uint64(cfg.slots - 1)
        return o.astype(np.int64), b.astype(np.int64)

    prior = np.concatenate([kh, kh2, kh3, khp])
    po, pb = shard_bucket(prior)
    used = set(zip(po.tolist(), pb.tolist()))

    # two targets sharing one FREE (shard, bucket) — no earlier-phase
    # resident can expire mid-drive and open a way
    cand = (
        np.arange(10_000_000, 10_040_000, dtype=np.uint64) << np.uint64(32)
    ) | np.uint64(5)
    co, cb = shard_bucket(cand)
    free = [
        i for i in range(cand.shape[0])
        if (int(co[i]), int(cb[i])) not in used
    ]
    home = (int(co[free[0]]), int(cb[free[0]]))
    pair = [i for i in free if (int(co[i]), int(cb[i])) == home][:2]
    assert len(pair) == 2, "no bucket-sharing target pair found"
    k_sld, k_gcra = cand[pair[0]], cand[pair[1]]

    # way-saturate the home bucket: cfg.rows immortal fillers
    fcand = (np.arange(1, 400_000, dtype=np.uint64) << np.uint64(32)) | (
        np.uint64(9)
    )
    fo, fb = shard_bucket(fcand)
    fsel = np.flatnonzero((fo == home[0]) & (fb == home[1]))[: cfg.rows]
    assert fsel.shape[0] == cfg.rows, "filler search exhausted"
    fillers = fcand[fsel]
    nf = fillers.shape[0]
    onesF = np.ones(nf, np.int64)
    t = T0 + 40
    sF, _, _, _ = eng.decide_arrays(
        fillers, onesF, onesF * 1000, onesF * 1_000_000_000,
        np.zeros(nf, np.int32), np.zeros(nf, bool), t,
    )
    assert (sF == 0).all(), sF

    I32_MAX = (1 << 31) - 1
    DUR, LIM = 10_000, 4
    epoch = T0 - 1  # pinned at the engine's first decide (T0)
    charges = {2: {}, 3: {}}
    windows = set()
    for dt in (1, 1, 1, 1, 1, 3000, 1, 1, 6000, 1, 1, 15_000,
               1, 1, 1, 1, 25_001, 1, 2, 3, 9_999, 1):
        t += dt
        e_now = t - epoch
        wid = e_now // DUR
        windows.add(wid)
        exp = {}
        for algo_id, key in ((2, k_sld), (3, k_gcra)):
            cur = charges[algo_id].get(wid, 0)
            prev = charges[algo_id].get(wid - 1, 0)
            if algo_id == 2:
                budget, wend = sketch_sliding_budget(
                    cur, prev, e_now, LIM, DUR
                )
                reset = epoch + wend
            else:
                budget, tatq = sketch_gcra_budget(
                    cur, prev, e_now, LIM, DUR
                )
                T_, tau = gcra_params(LIM, DUR)
                tatq_c = min(tatq, I32_MAX)
                if budget >= 1:
                    reset = epoch + min(tatq_c + T_, I32_MAX)
                else:
                    reset = epoch + min(tatq_c + T_ - tau, I32_MAX)
            exp[algo_id] = (budget, reset)
        bkh = np.concatenate([fillers, [k_sld], [k_gcra]])
        bh = np.concatenate([np.zeros(nf, np.int64), [1, 1]])
        bl = np.full(nf + 2, LIM, np.int64)
        bl[:nf] = 1000
        bd = np.full(nf + 2, DUR, np.int64)
        bd[:nf] = 1_000_000_000
        ba = np.concatenate(
            [np.zeros(nf, np.int32), np.asarray([2, 3], np.int32)]
        )
        s, l, r, ts = eng.decide_arrays(
            bkh, bh, bl, bd, ba, np.zeros(nf + 2, bool), t
        )
        for row, algo_id in ((nf, 2), (nf + 1, 3)):
            budget, reset = exp[algo_id]
            charged = budget >= 1
            assert s[row] == (0 if charged else 1), (algo_id, t, s[row])
            assert r[row] == (budget - 1 if charged else 0), (algo_id, t)
            assert ts[row] == reset, (algo_id, t, int(ts[row]), reset)
            assert l[row] == LIM
            if charged:
                charges[algo_id][wid] = charges[algo_id].get(wid, 0) + 1
    assert len(windows) >= 3, "ring drive never crossed rotations"
    assert sum(charges[2].values()) > 0 and sum(charges[3].values()) > 0
    print("RING-OK", flush=True)

    eng.close()
    print("LEADER-OK", flush=True)


if __name__ == "__main__":
    main()
