"""Hostile shared-memory peers (r18 satellite): a buggy or malicious
process on the other side of the lane must never wedge or crash the
bridge — and must never poison anybody else's connection.

Three tiers:

- ShmRing validation units: every class of lying ring state (indices
  out of bounds, torn record headers, zero/oversized/past-the-head
  record lengths) raises ShmProtocolError instead of reading garbage;
- live-bridge mutation corpus: a raw client negotiates a real lane,
  corrupts it, and the bridge tears down THAT session only (teardown
  counter up, control socket closed) while clean unix AND TCP
  connections keep serving;
- randomized index/data fuzz: seeded garbage into the shared header
  and data region; after every round the bridge still answers a clean
  probe within the call timeout (the never-wedge contract).

The client side is symmetric: a lying SERVER tears the client lane
down via on_torn, never a hang.
"""

import asyncio
import struct

import numpy as np
import pytest

from _util import free_ports
from gubernator_tpu.api.types import RateLimitReq, RateLimitResp, Status
from gubernator_tpu.client_geb import AsyncGebClient, read_hello
from gubernator_tpu.serve.edge_bridge import EdgeBridge
from gubernator_tpu.serve.shm import (
    FLAG_CLOSED,
    MAGIC_SHM_OK,
    MAGIC_SHM_REQ,
    ShmClientLane,
    ShmProtocolError,
    ShmRing,
    _OFF_C2S_HEAD,
    _OFF_C2S_SEQ,
    _OFF_S2C_HEAD,
    _OFF_S2C_SEQ,
)

_U32 = struct.Struct("<I")
_DATA_OFF = 4096


def _req(key):
    return RateLimitReq(
        name="hostile", unique_key=key, hits=1, limit=9,
        duration=60_000,
    )


class FakeInstance:
    async def get_rate_limits(self, reqs, stage_frame=False):
        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=r.limit - r.hits, reset_time=1,
            )
            for r in reqs
        ]


# -- ShmRing validation units ------------------------------------------------


def _pair(tmp_path):
    server = ShmRing.create(64, dir=str(tmp_path))
    client = ShmRing.open(server.path)
    return server, client


def test_ring_rejects_lying_indices(tmp_path):
    server, client = _pair(tmp_path)
    try:
        cap = server.c2s_cap
        # head beyond capacity: used > cap
        client._put_u64(_OFF_C2S_HEAD, cap + 999)
        with pytest.raises(ShmProtocolError, match="lying ring"):
            server.read_c2s(1 << 20)
        # head behind tail: used negative
        client._put_u64(_OFF_C2S_HEAD, 0)
        server._put_u64(_OFF_C2S_HEAD + 64, 8)  # c2s tail
        with pytest.raises(ShmProtocolError, match="lying ring"):
            server.read_c2s(1 << 20)
    finally:
        client.release()
        server.release()


def test_ring_rejects_torn_and_hostile_records(tmp_path):
    server, client = _pair(tmp_path)
    try:
        # used < 4: a record header can't even exist
        client._put_u64(_OFF_C2S_HEAD, 2)
        with pytest.raises(ShmProtocolError, match="torn record"):
            server.read_c2s(1 << 20)

        # zero-length record
        client._put_u64(_OFF_C2S_HEAD, 0)
        client._mm[_DATA_OFF:_DATA_OFF + 4] = _U32.pack(0)
        client._put_u64(_OFF_C2S_HEAD, 8)
        with pytest.raises(ShmProtocolError, match="outside"):
            server.read_c2s(1 << 20)

        # length past the door's bound
        client._mm[_DATA_OFF:_DATA_OFF + 4] = _U32.pack(0x7FFFFFF0)
        with pytest.raises(ShmProtocolError, match="outside"):
            server.read_c2s(1 << 20)

        # length beyond the published head (torn/hostile write)
        client._mm[_DATA_OFF:_DATA_OFF + 4] = _U32.pack(100)
        with pytest.raises(ShmProtocolError, match="beyond published"):
            server.read_c2s(1 << 20)
    finally:
        client.release()
        server.release()


def test_honest_roundtrip_survives_wraparound(tmp_path):
    """Control case: thousands of honest frames through a small ring
    wrap both directions many times without a validator false
    positive."""
    server, client = _pair(tmp_path)
    try:
        payload = b"x" * 700
        for i in range(1000):
            assert client.write_c2s(payload)
            assert server.read_c2s(1 << 20) == payload
            assert server.write_s2c(payload)
            assert client.read_s2c(1 << 20) == payload
    finally:
        client.release()
        server.release()


# -- live-bridge mutation corpus ---------------------------------------------


async def _negotiate_raw(path):
    """Speak the control protocol by hand: hello, GEBM, GEBN — and map
    the granted ring directly (the hostile peer's view)."""
    reader, writer = await asyncio.open_unix_connection(path)
    hello = await read_hello(reader)
    assert hello.shm
    writer.write(struct.pack("<II", MAGIC_SHM_REQ, 0))
    await writer.drain()
    magic, plen = struct.unpack("<II", await reader.readexactly(8))
    assert magic == MAGIC_SHM_OK and plen > 0
    await reader.readexactly(16)  # caps
    ring_path = (await reader.readexactly(plen)).decode()
    return reader, writer, ShmRing.open(ring_path)


async def _probe(endpoint):
    """One clean decision through a throwaway connection."""
    c = AsyncGebClient(endpoint, shm="off", timeout=10.0)
    try:
        resps = await c.get_rate_limits([_req("probe")])
        assert resps[0].status == Status.UNDER_LIMIT
    finally:
        await c.close()


def _mutations():
    def lying_head(ring):
        ring._put_u64(_OFF_C2S_HEAD, ring.c2s_cap + 12345)

    def zero_len_record(ring):
        ring._mm[_DATA_OFF:_DATA_OFF + 4] = _U32.pack(0)
        ring._put_u64(_OFF_C2S_HEAD, 8)
        ring._bump_wake(_OFF_C2S_SEQ)

    def oversized_len(ring):
        ring._mm[_DATA_OFF:_DATA_OFF + 4] = _U32.pack(0x7FFFFFF0)
        ring._put_u64(_OFF_C2S_HEAD, 8)
        ring._bump_wake(_OFF_C2S_SEQ)

    def torn_header(ring):
        ring._put_u64(_OFF_C2S_HEAD, 2)
        ring._bump_wake(_OFF_C2S_SEQ)

    def len_beyond_head(ring):
        ring._mm[_DATA_OFF:_DATA_OFF + 4] = _U32.pack(5000)
        ring._put_u64(_OFF_C2S_HEAD, 8)
        ring._bump_wake(_OFF_C2S_SEQ)

    return [
        lying_head, zero_len_record, oversized_len, torn_header,
        len_beyond_head,
    ]


def test_bridge_tears_down_hostile_lane_only(tmp_path):
    """Every deterministic mutation kills ITS lane (teardown counted,
    control socket closed) and nothing else: a clean unix client, a
    clean TCP client, and a NEW shm negotiation all keep working."""
    from gubernator_tpu.serve import metrics

    path = str(tmp_path / "b.sock")
    (port,) = free_ports(1)

    async def run():
        bridge = EdgeBridge(
            FakeInstance(), path,
            tcp_address=f"127.0.0.1:{port}",
            shm_enabled=True, shm_ring_kib=64,
        )
        await bridge.start()
        # a long-lived CLEAN shm client that must survive every
        # hostile neighbor's teardown
        bystander = AsyncGebClient(f"unix:{path}", shm="require")
        await bystander.connect()
        try:
            for mutate in _mutations():
                before = metrics.GEB_SHM_TEARDOWNS._value.get()
                reader, writer, ring = await _negotiate_raw(path)
                try:
                    mutate(ring)
                    # the bridge must notice and close THIS control
                    # connection (EOF) — bounded, never a wedge
                    eof = await asyncio.wait_for(reader.read(1), 5.0)
                    assert eof == b"", f"{mutate.__name__}: no EOF"
                    assert (
                        metrics.GEB_SHM_TEARDOWNS._value.get() > before
                    ), f"{mutate.__name__}: teardown not counted"
                finally:
                    writer.close()
                    ring.release()
                # neighbors unpoisoned: unix, TCP, and the bystander's
                # still-mapped lane all serve
                await _probe(f"unix:{path}")
                await _probe(f"127.0.0.1:{port}")
                r = await bystander.get_rate_limits([_req("by")])
                assert r[0].status == Status.UNDER_LIMIT
            assert bystander.stats()["transport"] == "shm"
        finally:
            await bystander.close()
            await bridge.stop()

    asyncio.run(run())


def test_bridge_survives_randomized_ring_fuzz(tmp_path):
    """Seeded garbage into the shared index words and data region.
    After every round the bridge answers a clean probe — it may tear
    the fuzzed lane down or ignore still-valid state, but it must
    never wedge, crash, or stop serving."""
    path = str(tmp_path / "b.sock")
    rng = np.random.default_rng(18)

    async def run():
        bridge = EdgeBridge(
            FakeInstance(), path, shm_enabled=True, shm_ring_kib=64
        )
        await bridge.start()
        try:
            for round_i in range(12):
                reader, writer, ring = await _negotiate_raw(path)
                try:
                    for _ in range(int(rng.integers(1, 5))):
                        off = int(rng.integers(64, 288))
                        blob = rng.bytes(int(rng.integers(1, 16)))
                        ring._mm[off:off + len(blob)] = blob
                    if rng.integers(2):
                        blob = rng.bytes(int(rng.integers(8, 512)))
                        ring._mm[_DATA_OFF:_DATA_OFF + len(blob)] = blob
                        ring._put_u64(
                            _OFF_C2S_HEAD, int(rng.integers(1, 1 << 17))
                        )
                    ring._bump_wake(_OFF_C2S_SEQ)
                    # give the server a beat to react either way
                    try:
                        await asyncio.wait_for(reader.read(1), 0.3)
                    except asyncio.TimeoutError:
                        pass  # state happened to stay valid: fine
                finally:
                    writer.close()
                    ring.release()
                await _probe(f"unix:{path}")
        finally:
            await bridge.stop()

    asyncio.run(run())


# -- lying server vs the client lane -----------------------------------------


def test_client_lane_tears_down_on_lying_server(tmp_path):
    """The validation is symmetric: a server that publishes lying s2c
    indices fires the client's on_torn (bounded), try_send goes dead,
    and the lane never hangs the client loop."""

    async def run():
        server = ShmRing.create(64, dir=str(tmp_path))
        lane = ShmClientLane(server.path)
        torn = asyncio.get_running_loop().create_future()

        def on_frame(data):
            pass

        def on_torn(exc):
            if not torn.done():
                torn.set_result(exc)

        lane.start(
            asyncio.get_running_loop(), on_frame, on_torn,
            max_resp_len=1 << 20,
        )
        try:
            assert lane.try_send(b"x" * 64)
            server._put_u64(_OFF_S2C_HEAD, server.s2c_cap + 77)
            server._bump_wake(_OFF_S2C_SEQ)
            exc = await asyncio.wait_for(torn, 5.0)
            assert isinstance(exc, ShmProtocolError)
            assert lane.try_send(b"y" * 64) is False
        finally:
            lane.close()
            server.release()

    asyncio.run(run())


def test_client_lane_sees_server_close_flag(tmp_path):
    """A server that vanishes politely (CLOSED flag) also surfaces as
    a torn lane, not a hang."""

    async def run():
        server = ShmRing.create(64, dir=str(tmp_path))
        lane = ShmClientLane(server.path)
        torn = asyncio.get_running_loop().create_future()
        lane.start(
            asyncio.get_running_loop(),
            lambda data: None,
            lambda exc: (not torn.done()) and torn.set_result(exc),
            max_resp_len=1 << 20,
        )
        try:
            server.mark_closed(server_side=True)
            await asyncio.wait_for(torn, 5.0)
            assert lane.try_send(b"z" * 16) is False
        finally:
            lane.close()
            server.release()

    asyncio.run(run())
