"""Membership change under a LIVE edge: GEBR refusal -> ring refresh
-> re-route (the r5 over-admission guard, end to end).

The bridge side is unit-tested in test_edge_bridge.py; this drives the
real C++ edge binary against in-process bridges whose membership is
swapped mid-run:

1. edge boots with a 1-node ring and fast-paths everything locally;
2. the picker is swapped to a 2-node ring (as etcd/k8s discovery does
   via set_peers) whose second node is ANOTHER in-process bridge on
   TCP;
3. the edge's next fast frame is refused (GEBR) — those items come
   back as per-item "membership changed; retry" errors, never decided
   under the stale view;
4. within the refresh period the edge re-reads the ring and
   subsequent requests reach BOTH bridges, split by the new ring.
"""

import asyncio
import json
import struct
import subprocess
import time
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.serve.edge_bridge import EdgeBridge

from tests._util import edge_binary

EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

NODE_A = "10.99.0.1:81"  # the edge's primary (unix socket)
NODE_B = "10.99.0.2:81"  # joins later, bridge on 127.0.0.1 TCP


class FakeBackend:
    decide_submit_arrays = object()
    decide_submit = object()


class FakePicker:
    def __init__(self, hosts_self):
        self._peers = [
            type("P", (), {"host": h, "is_owner": mine})()
            for h, mine in hosts_self
        ]

    def peers(self):
        return self._peers


class CountingInstance:
    """Array fast path that counts items and echoes limit-hits as
    remaining (so decisions are checkable), plus a string path."""

    def __init__(self, self_host, hosts):
        self.backend = FakeBackend()
        self.picker = FakePicker(
            [(h, h == self_host) for h in hosts]
        )
        self.fast_items = 0
        inst = self

        class B:
            async def decide_arrays(self, fields, frame=True):
                n = fields["key_hash"].shape[0]
                inst.fast_items += n
                return (
                    np.zeros(n, np.int64),
                    fields["limit"],
                    fields["limit"] - fields["hits"],
                    np.zeros(n, np.int64),
                )

        class T:
            def observe_hashes(self, h):
                pass

        self.batcher = B()
        self.traffic = T()

    async def get_rate_limits(self, reqs, stage_frame=False):
        from gubernator_tpu.api.types import RateLimitResp, Status

        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=r.limit - r.hits, reset_time=1,
            )
            for r in reqs
        ]


def _post(port, n_keys, tag):
    # bounded 503 retry (r15 deflake; see tests/_util.post_json): a
    # just-(re)spawned edge can refuse the first frame un-served
    # under full-suite load
    from tests._util import post_json

    return post_json(
        f"http://127.0.0.1:{port}/v1/GetRateLimits",
        {
            "requests": [
                {"name": "rc", "uniqueKey": f"{tag}-{i}", "hits": 1,
                 "limit": 7, "duration": 60000}
                for i in range(n_keys)
            ]
        },
        timeout=15,
    )


def test_membership_change_refuses_then_reroutes():
    from tests._util import free_ports

    edge_http, bridge_b_tcp = free_ports(2)
    sock_a = "/tmp/guber-ring-change-a.sock"

    async def main():
        inst_a = CountingInstance(NODE_A, [NODE_A])
        inst_b = CountingInstance(NODE_B, [NODE_A, NODE_B])
        bridge_a = EdgeBridge(
            inst_a, sock_a,
            peer_bridges={NODE_B: f"127.0.0.1:{bridge_b_tcp}"},
        )
        bridge_b = EdgeBridge(
            inst_b, "", tcp_address=f"127.0.0.1:{bridge_b_tcp}"
        )
        import os

        try:
            os.unlink(sock_a)
        except FileNotFoundError:
            pass
        await bridge_a.start()
        await bridge_b.start()
        edge = subprocess.Popen(
            [str(EDGE_BIN), "--listen", str(edge_http),
             "--backend", sock_a, "--ring-refresh-ms", "100",
             "--batch-wait-us", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            deadline = time.monotonic() + 10
            import socket as sl

            while True:
                if edge.poll() is not None:
                    pytest.fail(f"edge died:\n{edge.stdout.read()}")
                try:
                    sl.create_connection(
                        ("127.0.0.1", edge_http), timeout=1
                    ).close()
                    break
                except OSError:
                    assert time.monotonic() < deadline
                    await asyncio.sleep(0.05)

            # phase 1: 1-node ring, everything fast + local
            out = await asyncio.to_thread(_post, edge_http, 20, "p1")
            assert all(
                r["remaining"] == "6" and not r["error"]
                for r in out["responses"]
            )
            assert inst_a.fast_items == 20 and inst_b.fast_items == 0

            # phase 2: membership grows (the discovery callback shape:
            # a NEW picker object swapped in, as set_peers does)
            inst_a.picker = FakePicker(
                [(NODE_A, True), (NODE_B, False)]
            )

            # the edge still has the old ring for up to refresh-ms; its
            # next fast frames are REFUSED, never decided locally under
            # the stale view. Items answer with retry errors until the
            # re-read lands; then both bridges serve their shares.
            saw_retry = False
            deadline = time.monotonic() + 8
            while time.monotonic() < deadline:
                out = await asyncio.to_thread(_post, edge_http, 30, "p2")
                errs = [r["error"] for r in out["responses"] if r["error"]]
                if errs:
                    assert all(
                        "membership changed" in e for e in errs
                    ), errs
                    saw_retry = True
                if inst_b.fast_items > 0 and not errs:
                    break
                await asyncio.sleep(0.1)
            assert inst_b.fast_items > 0, (
                "edge never re-routed to the new node "
                f"(a={inst_a.fast_items}, b={inst_b.fast_items}, "
                f"saw_retry={saw_retry})"
            )
            # under the stale view nothing may have been decided by A
            # for keys B owns: A's count can only have grown through
            # frames accepted AFTER its ring matched (post-change
            # acceptance implies the edge's fingerprint matched the
            # 2-node membership)
        finally:
            edge.kill()
            await bridge_a.stop()
            await bridge_b.stop()

    asyncio.run(main())


def test_degraded_cluster_folds_self_fast_into_slow_frame():
    """Cluster whose peers have NO reachable bridges (e.g. GUBER_EDGE_TCP
    unset fleet-wide): most items fold to the string path anyway, and
    splitting off a minority self-fast frame would cost a second backend
    round-trip per request for nothing (measured ~15% door throughput on
    the 6-node exact bench). The router must send ONE string frame:
    the bridge's fast counter stays 0 while decisions stay correct.
    Converse guard: the single-NODE ring (self-fast majority, no slow)
    must still use the fast path."""
    from tests._util import free_ports

    edge_http, = free_ports(1)
    sock_a = "/tmp/guber-fold-a.sock"
    # 3-node ring, peers WITHOUT bridge endpoints: self owns ~1/3
    nodes = [NODE_A, "10.99.0.3:81", "10.99.0.4:81"]

    async def main():
        import os

        inst = CountingInstance(NODE_A, nodes)  # no peer_bridges map
        bridge = EdgeBridge(inst, sock_a)
        try:
            os.unlink(sock_a)
        except FileNotFoundError:
            pass
        await bridge.start()
        edge = subprocess.Popen(
            [str(EDGE_BIN), "--listen", str(edge_http),
             "--backend", sock_a, "--ring-refresh-ms", "100",
             "--batch-wait-us", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            deadline = time.monotonic() + 10
            import socket as sl

            while True:
                if edge.poll() is not None:
                    pytest.fail(f"edge died:\n{edge.stdout.read()}")
                try:
                    sl.create_connection(
                        ("127.0.0.1", edge_http), timeout=1
                    ).close()
                    break
                except OSError:
                    assert time.monotonic() < deadline
                    await asyncio.sleep(0.05)

            out = await asyncio.to_thread(_post, edge_http, 60, "fold")
            assert all(
                r["remaining"] == "6" and not r["error"]
                for r in out["responses"]
            ), out["responses"][:3]
            # ONE string frame served everything: the pre-hashed path
            # was never used even for self-owned items
            assert inst.fast_items == 0, inst.fast_items

            # converse: shrink to a 1-node ring -> fast path again
            inst.picker = FakePicker([(NODE_A, True)])
            deadline = time.monotonic() + 8
            while time.monotonic() < deadline and inst.fast_items == 0:
                out = await asyncio.to_thread(
                    _post, edge_http, 10, f"f1-{time.monotonic_ns()}"
                )
                await asyncio.sleep(0.1)
            assert inst.fast_items > 0, "fast path never re-engaged"
        finally:
            edge.kill()
            await bridge.stop()

    asyncio.run(main())
