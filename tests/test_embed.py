"""Library-embedding contract (VERDICT r5 gap 1).

The reference explicitly supports embedding: the application owns the
grpc server and drives peer membership itself (reference config.go:29-30,
architecture.md:79-91). Here the same seam: `register_servicers` puts
the V1 + PeersV1 services on a CALLER-OWNED `grpc.aio` server, and the
caller calls `Instance.set_peers` from its own discovery — no `Server`,
no HTTP gateway, no discovery pool.
"""

import asyncio

import grpc
import pytest

from gubernator_tpu.api.grpc_glue import PeersV1Stub, V1Stub
from gubernator_tpu.api.proto.gen import gubernator_pb2, peers_pb2
from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import Instance
from gubernator_tpu.serve.server import make_backend, register_servicers


def test_embed_in_caller_owned_grpc_server():
    async def scenario():
        # the embedding app's own server — gubernator never sees its
        # lifecycle, interceptors, or ports
        app_server = grpc.aio.server()
        port = app_server.add_insecure_port("127.0.0.1:0")
        assert port != 0

        conf = ServerConfig(backend="exact")
        instance = Instance(conf, make_backend(conf))
        instance.start()  # batcher + gossip tasks on the running loop
        assert register_servicers(app_server, instance) is instance
        await app_server.start()
        try:
            # caller-driven membership: the app's discovery calls
            # set_peers directly, marking this node's own address
            addr = f"127.0.0.1:{port}"
            await instance.set_peers(
                [PeerInfo(address=addr, is_owner=True)]
            )

            chan = grpc.aio.insecure_channel(addr)
            v1 = V1Stub(chan)
            h = await v1.HealthCheck(gubernator_pb2.HealthCheckReq())
            assert h.status == "healthy" and h.peer_count == 1

            resp = await v1.GetRateLimits(
                gubernator_pb2.GetRateLimitsReq(
                    requests=[
                        gubernator_pb2.RateLimitReq(
                            name="embed", unique_key="k", hits=1,
                            limit=5, duration=10_000,
                        )
                    ]
                )
            )
            assert resp.responses[0].limit == 5
            assert resp.responses[0].remaining == 4

            # the peer-facing service is registered too (another node
            # can forward to an embedded instance)
            peers = PeersV1Stub(chan)
            presp = await peers.GetPeerRateLimits(
                peers_pb2.GetPeerRateLimitsReq(
                    requests=[
                        gubernator_pb2.RateLimitReq(
                            name="embed", unique_key="k", hits=1,
                            limit=5, duration=10_000,
                        )
                    ]
                )
            )
            assert presp.rate_limits[0].remaining == 3

            # membership swap is the caller's call, not a pool's:
            # a second (not-yet-reachable — gRPC dials lazily) peer
            # appears in the ring the moment the app says so
            await instance.set_peers(
                [
                    PeerInfo(address=addr, is_owner=True),
                    PeerInfo(address="127.0.0.1:1", is_owner=False),
                ]
            )
            h = await v1.HealthCheck(gubernator_pb2.HealthCheckReq())
            assert h.peer_count == 2

            await chan.close()
        finally:
            await app_server.stop(grace=None)
            await instance.stop()

    asyncio.run(scenario())


def test_embed_requires_no_server_object():
    """The embed seam must not depend on serve.server.Server internals:
    an Instance alone (no Server, no HTTP, no discovery) serves and
    stops cleanly inside a foreign event loop."""

    async def scenario():
        conf = ServerConfig(backend="exact")
        instance = Instance(conf, make_backend(conf))
        instance.start()
        out = await instance.get_rate_limits([])
        assert out == []
        await instance.stop()

    asyncio.run(scenario())
