"""Mesh backend through the FULL serving stack: the 8-device CPU mesh
engine behind a real gRPC server (instance + batcher + warmup), not just
the engine-level suite in test_sharded.py.

Covers the production wiring GUBER_BACKEND=mesh uses: warmup compiles
the sub-batch rung ladder through the public decide path, the pipelined
decide_submit/decide_wait split engages via the batcher, GLOBAL owned
keys broadcast-and-install across the mesh shards, and the oracle
semantics hold over the wire.
"""

import time

import pytest

from _util import free_ports
from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    Status,
)
from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import LocalCluster
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve.backends import MeshBackend


@pytest.fixture(scope="module")
def mesh_cluster():
    import jax

    assert len(jax.devices()) == 8, "conftest should provide 8 cpu devices"

    def factory():
        # tiny ladder + store: warmup compiles a handful of CPU programs
        return MeshBackend(
            StoreConfig(rows=16, slots=256), buckets=(64,)
        )

    cluster = LocalCluster(
        [f"127.0.0.1:{p}" for p in free_ports(1)],
        backend_factory=factory,
    )
    cluster.start()
    yield cluster
    cluster.stop()


def test_mesh_backend_served_transitions(mesh_cluster):
    """Token 2->1->0->OVER and a leaky drain, decided by the sharded
    mesh engine behind real gRPC."""
    with V1Client(mesh_cluster.get_peer()) as client:
        seq = []
        for _ in range(4):
            rl = client.get_rate_limits(
                [
                    RateLimitReq(
                        name="mesh-serve", unique_key="tok", hits=1,
                        limit=3, duration=60_000,
                    )
                ],
                timeout=20,
            )[0]
            seq.append((rl.status, rl.remaining))
        assert seq == [
            (Status.UNDER_LIMIT, 2),
            (Status.UNDER_LIMIT, 1),
            (Status.UNDER_LIMIT, 0),
            (Status.OVER_LIMIT, 0),
        ], seq

        leaky = client.get_rate_limits(
            [
                RateLimitReq(
                    name="mesh-serve", unique_key="lk", hits=2, limit=4,
                    duration=2_000, algorithm=Algorithm.LEAKY_BUCKET,
                )
            ],
            timeout=20,
        )[0]
        assert (leaky.status, leaky.remaining) == (Status.UNDER_LIMIT, 2)


def test_mesh_backend_served_global(mesh_cluster):
    """GLOBAL behavior on the mesh backend: the single node owns every
    key, so a GLOBAL decide charges locally and queues a broadcast; the
    replica-install path (update_globals through the batcher into the
    mesh _upsert collective) must keep the key's state consistent over
    repeated reads."""
    with V1Client(mesh_cluster.get_peer()) as client:
        def hit(hits):
            return client.get_rate_limits(
                [
                    RateLimitReq(
                        name="mesh-serve", unique_key="g", hits=hits,
                        limit=5, duration=60_000,
                        behavior=Behavior.GLOBAL,
                    )
                ],
                timeout=20,
            )[0]

        first = hit(1)
        assert (first.status, first.remaining) == (Status.UNDER_LIMIT, 4)
        time.sleep(0.3)  # let the broadcast loop run at least once
        second = hit(1)
        assert (second.status, second.remaining) == (
            Status.UNDER_LIMIT, 3,
        )
        peek = hit(0)
        assert peek.remaining == 3
