"""The one partitioned engine (r14): policy degenerate case, 1-vs-N
serving-pipeline identity, the sharded sketch tier's error bound, and
the in-mesh GLOBAL psum prototype.

What's pinned here:

- the sharding policy object (parallel/policy.py): the single-device
  policy is the DEGENERATE case of the same engine class, and a
  1-device mesh policy is decision-identical to it even under
  eviction pressure (same table, same kernel — only the dispatch
  wrapper differs);
- shard-count 1 vs N differential fuzz through the REAL serving
  pipeline (instance -> batcher -> arrival prep -> merged submit ->
  kernel) under the r10 fake clock: byte-identical decisions on
  exact-tier keys (no tier pressure, so sharding the table cannot
  change bucket occupancy);
- the sharded sketch tier: per-shard sub-sketches charge only their
  owner's keys, estimates never under-count, and the max overestimate
  stays within the per-shard e*N_s/width bound (N_s = that shard's
  charged total <= the global N — sharding tightens the classic
  count-min bound, never loosens it);
- apply_global_hits: the owner-charge + psum-replicate + install
  collective equals the sequential owner decide, flat == mesh.
"""

import asyncio
import math

import numpy as np
import pytest

import gubernator_tpu.core  # noqa: F401  (x64)
from gubernator_tpu.api.types import (
    Algorithm,
    PeerInfo,
    RateLimitReq,
)
from gubernator_tpu.core.sketches import SketchConfig
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.parallel.policy import ShardingPolicy
from gubernator_tpu.parallel.sharded import (
    MeshEngine,
    PartitionedEngine,
    TpuEngine,
    owner_of_np,
)
from gubernator_tpu.serve.backends import MeshBackend, TpuBackend
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import Instance

T0 = 1_700_000_000_000
ADDR = "127.0.0.1:7975"


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self) -> int:
        return self.t


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


# -- policy ------------------------------------------------------------------


def test_policy_factories_and_degenerate_shape():
    import jax

    single = ShardingPolicy.single()
    assert single.flat and single.n_shards == 1 and single.mesh is None
    assert "degenerate" in single.describe()

    mesh = ShardingPolicy.over_mesh()
    assert not mesh.flat
    assert mesh.n_shards == len(jax.devices()) == 8
    assert mesh.axes == ("shard",) and not mesh.spans_processes
    assert mesh.store_spec() == mesh.request_spec()

    two_d = ShardingPolicy.over_mesh(mesh_shape=(4, 2))
    assert two_d.axes == ("host", "chip") and two_d.hierarchical
    with pytest.raises(ValueError):
        ShardingPolicy.over_mesh(mesh_shape=(3, 2))


def test_engine_classes_are_one_implementation():
    """TpuEngine and MeshEngine are constructor shims over ONE class —
    the no-drift property the r14 unification is for."""
    assert issubclass(TpuEngine, PartitionedEngine)
    assert issubclass(MeshEngine, PartitionedEngine)
    flat = TpuEngine(StoreConfig(rows=4, slots=256), buckets=(64,))
    mesh = MeshEngine(StoreConfig(rows=4, slots=256), buckets=(64,))
    for name in (
        "decide_submit", "decide_wait", "prep_run", "merge_prepped",
        "decide_submit_merged", "decide_submit_presorted",
        "snapshot_read", "live_mask", "install_windows",
        "update_globals", "sync_globals", "apply_global_hits",
        "sketch_estimates", "promote_from_sketch", "warmup",
    ):
        assert (
            getattr(type(flat), name, None)
            is getattr(PartitionedEngine, name)
        ), f"{name} forked on TpuEngine"
        assert (
            getattr(type(mesh), name, None)
            is getattr(PartitionedEngine, name)
        ), f"{name} forked on MeshEngine"


def test_single_vs_one_shard_mesh_identical_under_pressure():
    """A 1-device mesh policy IS the degenerate case: same table
    geometry, same kernel — decisions stay byte-identical even under
    way-exhaustion pressure where an N-shard split would change bucket
    occupancy."""
    import jax

    flat = TpuEngine(StoreConfig(rows=1, slots=16), buckets=(64, 256))
    mesh1 = MeshEngine(
        StoreConfig(rows=1, slots=16),
        devices=jax.devices()[:1],
        buckets=(64, 256),
    )
    assert mesh1.n == 1
    rng = np.random.default_rng(3)
    for step in range(12):
        n = int(rng.integers(1, 120))
        kh = rng.integers(1, 1 << 63, n).astype(np.uint64)
        hits = rng.integers(0, 4, n).astype(np.int64)
        lim = np.full(n, 5, np.int64)
        dur = np.full(n, 60_000, np.int64)
        algo = rng.integers(0, 2, n).astype(np.int32)
        gnp = np.zeros(n, bool)
        a = flat.decide_arrays(kh, hits, lim, dur, algo, gnp, T0 + step)
        b = mesh1.decide_arrays(kh, hits, lim, dur, algo, gnp, T0 + step)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # pressure actually happened (1-way 16-bucket table, ~700 keys)
    assert flat.stats.snapshot()["evictions"] + flat.stats.snapshot()[
        "dropped"
    ] > 0


# -- 1-vs-N serving-pipeline differential fuzz -------------------------------


def test_shard_count_identity_through_serving_pipeline(monkeypatch):
    """Shard-count 1 vs N, byte-identical through the REAL pipeline
    (instance -> batcher -> arrival prep -> merged submit -> shard_map
    kernel) under the r10 fake clock, exact-tier keys (roomy store, so
    the N-way table split cannot change occupancy). The sketch tier is
    ON for both sides — the r14 mesh tier must keep the no-pressure
    byte-identity the flat tier has."""
    import jax

    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    def be(n_shards: int):
        store = StoreConfig(rows=16, slots=1 << 10)
        sketch = SketchConfig(rows=4, width=1 << 12)
        if n_shards == 1:
            return TpuBackend(store, buckets=(16, 64), sketch=sketch)
        return MeshBackend(
            store,
            devices=jax.devices()[:n_shards],
            buckets=(16, 64),
            sketch=sketch,
        )

    async def mk(n_shards: int):
        conf = ServerConfig(
            grpc_address=ADDR, advertise_address=ADDR,
            backend="tpu" if n_shards == 1 else "mesh",
            sketch_sync_wait=600.0,  # no promoter flush mid-fuzz
        )
        inst = Instance(conf, be(n_shards))
        inst.start()
        await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
        return inst

    async def run():
        one = await mk(1)
        eight = await mk(8)
        assert eight.backend.engine.n == 8
        if one.shed is not None:
            one.shed.now_fn = clock
        if eight.shed is not None:
            eight.shed.now_fn = clock
        try:
            rng = np.random.default_rng(17)
            keys = [f"p{i}" for i in range(40)]
            for step in range(120):
                clock.t += int(rng.choice([0, 1, 9, 200, 2500]))
                n = int(rng.integers(1, 9))
                batch = [
                    RateLimitReq(
                        name="shardfuzz",
                        unique_key=keys[int(rng.integers(len(keys)))],
                        hits=int(rng.choice([0, 1, 1, 2, 7])),
                        limit=int(rng.choice([1, 2, 3, 50])),
                        duration=int(rng.choice([400, 2000, 60_000])),
                        algorithm=Algorithm(int(rng.integers(2))),
                    )
                    for _ in range(n)
                ]
                a = await one.get_rate_limits(batch)
                b = await eight.get_rate_limits(batch)
                for x, y, r in zip(a, b, batch):
                    assert (
                        x.status, x.limit, x.remaining, x.reset_time,
                        x.error,
                    ) == (
                        y.status, y.limit, y.remaining, y.reset_time,
                        y.error,
                    ), (step, r, x, y)
            # no tier pressure on either side: identity was exact-tier
            assert one.backend.stats()["dropped"] == 0
            assert eight.backend.stats()["dropped"] == 0
        finally:
            await one.stop()
            await eight.stop()

    asyncio.run(run())


# -- sharded sketch tier ------------------------------------------------------


def _cover_all_buckets(n_shards: int, slots: int) -> np.ndarray:
    """One immortal filler key per (shard, bucket) pair — the mesh twin
    of cli/bench_serving._filler_hashes: with every way pinned live,
    later creates are provably sketch-served (live-victim protection)."""
    from gubernator_tpu.core import hashing
    from gubernator_tpu.core.store import _BUCKET_SALT

    need = {(s, b) for s in range(n_shards) for b in range(slots)}
    out = []
    v = 1
    while need:
        kh = np.uint64((v << 32) | 9)
        arr = np.asarray([kh], np.uint64)
        s = int(owner_of_np(arr, n_shards)[0])
        b = int(
            hashing.mix64(arr ^ _BUCKET_SALT)[0] & np.uint64(slots - 1)
        )
        if (s, b) in need:
            need.remove((s, b))
            out.append(kh)
        v += 1
    return np.asarray(out, np.uint64)


def test_sharded_sketch_error_bound_zero_undercount():
    """The acceptance property on the MESH tier: every bucket of every
    shard pinned live, measured keys all sketch-served; estimates
    never under-count and the max overestimate stays within the
    per-shard e*N_s/width bound."""
    slots, width = 16, 1 << 12
    eng = MeshEngine(
        StoreConfig(rows=1, slots=slots), buckets=(64, 256, 1024),
        sketch=SketchConfig(rows=4, width=width),
    )
    fillers = _cover_all_buckets(eng.n, slots)
    nf = fillers.shape[0]
    ones_f = np.ones(nf, np.int64)
    eng.decide_arrays(
        fillers, ones_f, ones_f * 1000, ones_f * 1_000_000_000,
        np.zeros(nf, np.int32), np.zeros(nf, bool), T0,
    )
    assert eng.stats.snapshot()["dropped"] == 0

    D, LIM = 600_000, 1_000_000
    n_keys = 300
    # fingerprint range disjoint from the fillers' (high-32 bits are
    # the tag): a tag collision inside a bucket would alias a measured
    # key onto a filler's entry and decide it exactly
    meas = (
        (np.arange(1, n_keys + 1, dtype=np.uint64) + np.uint64(10_000_000))
        << np.uint64(32)
    ) | np.uint64(3)
    true = np.zeros(n_keys, np.int64)
    rng = np.random.default_rng(23)
    for step in range(6):
        hits_m = rng.integers(1, 5, n_keys).astype(np.int64)
        true += hits_m
        kh = np.concatenate([fillers, meas])
        hits = np.concatenate([np.zeros(nf, np.int64), hits_m])
        n = kh.shape[0]
        s, _, r, _ = eng.decide_arrays(
            kh, hits, np.full(n, LIM, np.int64),
            np.full(n, D, np.int64), np.zeros(n, np.int32),
            np.zeros(n, bool), T0 + 1 + step,
        )
    st = eng.stats.snapshot()
    assert st["dropped"] >= 6 * n_keys, st  # every measured decide hit the sketch
    assert st["evictions"] == 0, st  # live fillers never churned

    est = eng.sketch_estimates(meas, np.full(n_keys, D, np.int64), T0 + 50)
    under = int((est < true).sum())
    assert under == 0, f"{under} under-counts"
    # per-shard charged totals: the bound each shard's sub-sketch obeys
    owners = owner_of_np(meas, eng.n)
    over = (est - true).astype(np.int64)
    for s_i in range(eng.n):
        m = owners == s_i
        if not m.any():
            continue
        n_s = int(true[m].sum())
        bound = math.e * n_s / width
        assert over[m].max() <= max(bound, 0), (
            s_i, int(over[m].max()), bound
        )
    # and trivially within the global-N bound the flat tier documents
    assert over.max() <= math.e * int(true.sum()) / width


@pytest.mark.parametrize("algo", [2, 3], ids=["sliding", "gcra"])
def test_mesh_window_ring_pressure_is_fail_closed(algo):
    """The r21 window-ring on the 8-shard MESH tier: sliding/GCRA
    creates dropped to way exhaustion are served from the per-shard
    sub-rings and every served row is AT-LEAST-AS-RESTRICTIVE than the
    r15 bypass (the OFF engine answers each dropped create as a
    phantom-fresh window — maximally permissive). Every (shard,
    bucket) way is pinned with an immortal filler found-writer so
    measured creates provably drop in BOTH engines; the dt pool
    crosses rotation boundaries and multi-window jumps. Mirrors
    tests/test_sketch_tier.py::test_window_ring_pressure_is_fail_closed
    on the flat engine — sharding the ring (owner-charged sub-sketches,
    r14 layout) must not re-open the one-sidedness."""
    slots = 16
    mk = lambda sk: MeshEngine(  # noqa: E731
        StoreConfig(rows=1, slots=slots), buckets=(64, 256, 1024),
        sketch=SketchConfig(rows=4, width=1 << 12) if sk else None,
    )
    on, off = mk(True), mk(False)
    fillers = _cover_all_buckets(on.n, slots)
    nf = fillers.shape[0]
    ones_f = np.ones(nf, np.int64)
    for eng in (on, off):
        eng.decide_arrays(
            fillers, ones_f, ones_f * 1000, ones_f * 1_000_000_000,
            np.zeros(nf, np.int32), np.zeros(nf, bool), T0,
        )
        assert eng.stats.snapshot()["dropped"] == 0
    rng = np.random.default_rng(31)
    keyspace = 48
    pool = (
        (np.arange(1, keyspace + 1, dtype=np.uint64) + np.uint64(5_000_000))
        << np.uint64(32)
    ) | np.uint64(3)  # tag-disjoint from the fillers
    DUR, LIM = 10_000, 6
    t = T0
    diverged = 0
    for step in range(50):
        n = int(rng.integers(1, 24))
        kh_m = pool[rng.integers(0, keyspace, n)]
        hits_m = rng.choice((0, 1, 1, 1), n).astype(np.int64)
        t += int(rng.choice((0, 1, 7, 500, 2500, 12_000, 21_000)))
        kh = np.concatenate([fillers, kh_m])
        hits = np.concatenate([np.zeros(nf, np.int64), hits_m])
        lim = np.full(nf + n, LIM, np.int64)
        lim[:nf] = 1000
        dur = np.full(nf + n, DUR, np.int64)
        dur[:nf] = 1_000_000_000
        al = np.full(nf + n, algo, np.int32)
        al[:nf] = 0
        gnp = np.zeros(nf + n, bool)
        sa, _, ra, _ = on.decide_arrays(kh, hits, lim, dur, al, gnp, t)
        sb, _, rb, _ = off.decide_arrays(kh, hits, lim, dur, al, gnp, t)
        differ = (sa[nf:] != sb[nf:]) | (ra[nf:] != rb[nf:])
        diverged += int(differ.sum())
        assert (sa[nf:] >= sb[nf:]).all(), f"fail-open status @{step}"
        assert (ra[nf:] <= rb[nf:]).all(), f"fail-open remaining @{step}"
    assert diverged > 0, "mesh pressure fuzz never engaged the ring"
    st = on.stats.snapshot()
    assert st["dropped"] > 0
    assert st["evictions"] == 0, st  # live fillers never churned
    # the OFF engine (r15 bypass) never persisted a measured key
    assert not off.live_mask(pool, t).any()


def test_mesh_sketch_promoter_end_to_end():
    """Instance-level: the promoter runs on the MESH backend (fed by
    the all-shards estimate gather), promotes hot sketch keys into
    exact buckets on their owner shards, and GUBER_SKETCH=1 boots on
    GUBER_BACKEND=mesh."""
    conf = ServerConfig(
        grpc_address=ADDR, advertise_address=ADDR, backend="mesh",
        sketch_sync_wait=600.0, sketch_topk=64,
    )
    assert conf.sketch_config() is not None  # mesh carries the tier now
    backend = MeshBackend(
        StoreConfig(rows=1, slots=16), buckets=(64, 256),
        sketch=SketchConfig(rows=4, width=1 << 12),
    )
    assert backend.sketch_enabled

    async def run():
        inst = Instance(conf, backend)
        inst.start()
        await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
        try:
            assert inst.promoter is not None
            inst.promoter.tracker._next = 0.0
            import gubernator_tpu.serve.promoter as prom_mod

            orig = prom_mod.OBSERVE_MIN_INTERVAL_S
            prom_mod.OBSERVE_MIN_INTERVAL_S = 0.0
            try:
                reqs = [
                    RateLimitReq(
                        name="mp", unique_key=f"mk{j}", hits=1,
                        limit=2, duration=600_000,
                    )
                    for j in range(160)
                ]
                for _ in range(4):
                    await inst.get_rate_limits(reqs)
            finally:
                prom_mod.OBSERVE_MIN_INTERVAL_S = orig
            assert backend.stats()["dropped"] > 0
            await inst.promoter.flush_once()
            st = inst.promoter.stats()
            assert st["promotions"] > 0, st
            promoted = np.array(
                sorted(inst.promoter._promoted), np.uint64
            )
            assert backend.engine.live_mask(promoted).any()
        finally:
            await inst.stop()

    asyncio.run(run())


# -- in-mesh GLOBAL psum prototype -------------------------------------------


def test_apply_global_hits_matches_sequential_and_installs_replicas():
    """One collective = charge aggregated GLOBAL hits on each key's
    owner shard + psum-replicate the post-charge status + install
    replicas: results equal the flat engine's sequential decide, and
    every shard answers subsequent non-owner (gnp) reads from its
    replica without re-deciding."""
    flat = TpuEngine(StoreConfig(rows=4, slots=1 << 10), buckets=(64,))
    mesh = MeshEngine(StoreConfig(rows=4, slots=1 << 10), buckets=(64,))
    n = 24
    kh = (np.arange(1, n + 1, dtype=np.uint64) << np.uint64(32)) | (
        np.uint64(11)
    )
    # keys span several shards (the point of the psum)
    assert len(set(owner_of_np(kh, mesh.n).tolist())) > 2
    hits = (np.arange(n, dtype=np.int64) % 5) + 1
    lim = np.full(n, 10, np.int64)
    dur = np.full(n, 60_000, np.int64)

    rf = flat.apply_global_hits(kh, hits, lim, dur, T0)
    rm = mesh.apply_global_hits(kh, hits, lim, dur, T0)
    for a, b in zip(rf, rm):
        np.testing.assert_array_equal(
            np.asarray(a, np.int64), np.asarray(b, np.int64)
        )
    # second application keeps charging the SAME windows (owner state
    # is authoritative, not the replicas)
    rf2 = flat.apply_global_hits(kh, hits, lim, dur, T0 + 5)
    rm2 = mesh.apply_global_hits(kh, hits, lim, dur, T0 + 5)
    for a, b in zip(rf2, rm2):
        np.testing.assert_array_equal(
            np.asarray(a, np.int64), np.asarray(b, np.int64)
        )
    np.testing.assert_array_equal(
        np.asarray(rm2[2]), np.maximum(10 - 2 * hits, 0)
    )
    # replicas: gnp peeks answer the stored status on EVERY shard
    s, l, r, t = mesh.decide_arrays(
        kh, np.zeros(n, np.int64), lim, dur, np.zeros(n, np.int32),
        np.ones(n, bool), T0 + 6,
    )
    np.testing.assert_array_equal(
        np.asarray(r, np.int64), np.asarray(rm2[2], np.int64)
    )


def test_replication_snapshot_surface_on_mesh():
    """The r11 replication snapshot read works on the mesh backend now
    (it was gated off pre-r14): token windows snapshot identically to
    the flat engine's."""
    import jax

    flat = TpuBackend(StoreConfig(rows=4, slots=256), buckets=(64,))
    mesh = MeshBackend(
        StoreConfig(rows=4, slots=256),
        devices=jax.devices(),
        buckets=(64,),
    )
    assert mesh.snapshot_read is not None
    reqs = [
        RateLimitReq(
            name="snap", unique_key=f"s{i}", hits=2, limit=9,
            duration=60_000,
        )
        for i in range(12)
    ]
    flat.decide(reqs, [False] * 12, now=T0)
    mesh.decide(reqs, [False] * 12, now=T0)
    keys = [r.hash_key() for r in reqs] + ["never-seen"]
    a = flat.snapshot_read(keys, now=T0 + 1)
    b = mesh.snapshot_read(keys, now=T0 + 1)
    assert a == b
    assert a[-1] is None and a[0] == (9, 60_000, 7, T0 + 60_000, False)


def test_flat_sync_chunks_above_ladder_top():
    """Gossip batches above max(buckets) on the FLAT policy chunk
    through the decide ladder instead of refusing (the mesh branch of
    the same method extends its ladder — one class, no behavior fork),
    and the two policies stay decision-identical across the chunk
    boundary."""
    flat = TpuEngine(StoreConfig(rows=8, slots=1 << 11), buckets=(64,))
    mesh = MeshEngine(StoreConfig(rows=8, slots=1 << 11), buckets=(64,))
    rng = np.random.default_rng(0xC0DE)
    n = 150  # > max(buckets): two full chunks + a remainder on flat
    kh = rng.integers(1, 2**63, n, np.int64).astype(np.uint64)
    ones = np.ones(n, np.int64)
    lim, dur = ones * 5, ones * 60_000
    rf = flat.apply_global_hits(kh, ones, lim, dur, T0)
    rm = mesh.apply_global_hits(kh, ones, lim, dur, T0)
    for a, b in zip(rf, rm):
        np.testing.assert_array_equal(
            np.asarray(a, np.int64), np.asarray(b, np.int64)
        )
    np.testing.assert_array_equal(
        np.asarray(rf[2], np.int64), np.full(n, 4)
    )
    # the hits=0 gossip peek path chunks through the same funnel
    flat.sync_globals(kh, lim, dur, T0 + 5)
