"""Vendored k8s Endpoints client + K8sPool live round trips over real HTTP.

Same closure as the etcd side (tests/test_etcd_vendored.py): §2.10's
"contract-pinned but never executed" caveat dies here. An in-tree fake
API server speaks the actual Kubernetes REST watch protocol (HTTP/1.1,
chunked line-delimited JSON events, bearer-token check) and the
vendored client (serve/k8s_client.py) drives the full
initial-state → endpoint-added → endpoint-removed → close cycle through
a real socket. The same client runs unmodified against a live apiserver
(it loads the standard in-cluster config when constructed bare).
"""

import asyncio
import json
import socket
import threading
import time

import pytest

from gubernator_tpu.serve.k8s_client import (
    VendoredK8sApi,
    VendoredK8sWatch,
)


class FakeK8sApiServer:
    """Minimal apiserver: LIST + WATCH of one namespace's Endpoints."""

    def __init__(self, token: str = "test-token"):
        self.token = token
        self._lock = threading.Lock()
        self._subsets = []  # list of ip strings
        self._watchers = []  # sockets with open watch streams
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self._alive = True
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # -- test hooks ---------------------------------------------------------

    def set_ips(self, ips):
        """Replace the endpoints' addresses and push a MODIFIED event."""
        with self._lock:
            self._subsets = list(ips)
            ev = json.dumps(
                {"type": "MODIFIED", "object": self._endpoints_locked()}
            ).encode() + b"\n"
            dead = []
            for ws in self._watchers:
                try:
                    ws.sendall(_chunk(ev))
                except OSError:
                    dead.append(ws)
            for ws in dead:
                self._watchers.remove(ws)

    def watcher_count(self):
        with self._lock:
            return len(self._watchers)

    def stop(self):
        self._alive = False
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            for ws in self._watchers:
                try:
                    ws.close()
                except OSError:
                    pass
            self._watchers.clear()

    # -- internals ----------------------------------------------------------

    def _endpoints_locked(self) -> dict:
        return {
            "metadata": {"name": "guber", "resourceVersion": "1"},
            "subsets": [
                {"addresses": [{"ip": ip} for ip in self._subsets]}
            ]
            if self._subsets
            else [],
        }

    def _accept_loop(self):
        while self._alive:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn):
        try:
            reader = conn.makefile("rb")
            req = reader.readline().decode()
            headers = {}
            while True:
                line = reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            if headers.get("authorization") != f"Bearer {self.token}":
                conn.sendall(
                    b"HTTP/1.1 401 Unauthorized\r\n"
                    b"Content-Length: 0\r\n\r\n"
                )
                conn.close()
                return
            path = req.split()[1]
            if "watch=true" in path:
                conn.sendall(
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Transfer-Encoding: chunked\r\n\r\n"
                )
                with self._lock:
                    # initial state as a synthesized ADDED event — the
                    # real apiserver's behavior for rv-less watches
                    ev = json.dumps(
                        {
                            "type": "ADDED",
                            "object": self._endpoints_locked(),
                        }
                    ).encode() + b"\n"
                    conn.sendall(_chunk(ev))
                    self._watchers.append(conn)
                return  # connection stays open; events pushed by set_ips
            with self._lock:
                body = json.dumps(
                    {"kind": "EndpointsList",
                     "items": [self._endpoints_locked()]}
                ).encode()
            conn.sendall(
                b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                + f"Content-Length: {len(body)}\r\n\r\n".encode()
                + body
            )
            conn.close()
        except OSError:
            pass


def _chunk(data: bytes) -> bytes:
    return f"{len(data):x}\r\n".encode() + data + b"\r\n"


@pytest.fixture()
def fake():
    srv = FakeK8sApiServer()
    yield srv
    srv.stop()


@pytest.fixture()
def api(fake):
    return VendoredK8sApi(
        base_url=f"http://127.0.0.1:{fake.port}", token=fake.token
    )


def test_bad_token_rejected(fake):
    bad = VendoredK8sApi(
        base_url=f"http://127.0.0.1:{fake.port}", token="wrong"
    )
    with pytest.raises(RuntimeError, match="401"):
        bad.list_namespaced_endpoints("default")


def test_list_endpoints(api, fake):
    fake._subsets = ["10.0.0.1", "10.0.0.2"]
    out = api.list_namespaced_endpoints("default", label_selector="app=g")
    ips = [
        a.ip for e in out.items for s in e.subsets for a in s.addresses
    ]
    assert ips == ["10.0.0.1", "10.0.0.2"]


def test_watch_stream_initial_and_updates(api, fake):
    fake._subsets = ["10.0.0.1"]
    w = VendoredK8sWatch()
    got = []
    done = threading.Event()

    def consume():
        for ev in w.stream(
            api.list_namespaced_endpoints, "default",
            label_selector="app=g",
        ):
            ips = [
                a.ip for s in ev["object"].subsets for a in s.addresses
            ]
            got.append((ev["type"], ips))
            if len(got) >= 2:
                done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for _ in range(100):
        if fake.watcher_count():
            break
        time.sleep(0.02)
    fake.set_ips(["10.0.0.1", "10.0.0.9"])
    assert done.wait(timeout=10), got
    assert got[0] == ("ADDED", ["10.0.0.1"])
    assert got[1] == ("MODIFIED", ["10.0.0.1", "10.0.0.9"])
    w.stop()
    t.join(timeout=5)
    assert not t.is_alive()


def test_pool_full_cycle_against_fake(api, fake):
    """K8sPool over the vendored client: initial membership, a scale-up
    event, a scale-down event, clean close — all over a real socket."""
    from gubernator_tpu.serve.discovery import K8sPool

    fake._subsets = ["10.0.0.1", "10.0.0.2"]
    updates = []

    async def scenario():
        seen = asyncio.Event()

        async def on_update(peers):
            updates.append(
                sorted((p.address, p.is_owner) for p in peers)
            )
            seen.set()

        pool = K8sPool(
            namespace="default",
            selector="app=guber",
            pod_ip="10.0.0.2",
            pod_port="81",
            on_update=on_update,
            api=api,
            watch=VendoredK8sWatch(),
        )
        await pool.start()
        try:
            await asyncio.wait_for(seen.wait(), timeout=10)
            assert updates[-1] == [
                ("10.0.0.1:81", False),
                ("10.0.0.2:81", True),  # self marked owner by pod ip
            ]

            seen.clear()
            fake.set_ips(["10.0.0.1", "10.0.0.2", "10.0.0.3"])
            await asyncio.wait_for(seen.wait(), timeout=10)
            assert updates[-1] == [
                ("10.0.0.1:81", False),
                ("10.0.0.2:81", True),
                ("10.0.0.3:81", False),
            ]

            seen.clear()
            fake.set_ips(["10.0.0.2"])
            await asyncio.wait_for(seen.wait(), timeout=10)
            assert updates[-1] == [("10.0.0.2:81", True)]
        finally:
            await pool.close()

    asyncio.run(scenario())


def test_watch_error_event_raises_instead_of_wiping_peers(api, fake):
    """An ERROR watch event carries a Status object, not Endpoints;
    yielding it would push an EMPTY peer list through the pool. The
    client must raise (the kubernetes library's behavior) so the pool's
    retry path re-lists instead."""
    fake._subsets = ["10.0.0.1"]
    w = VendoredK8sWatch()
    stream = w.stream(api.list_namespaced_endpoints, "default")
    first = next(stream)  # synthesized ADDED
    assert first["type"] == "ADDED"
    with fake._lock:
        err = json.dumps(
            {"type": "ERROR",
             "object": {"kind": "Status", "message": "too old"}}
        ).encode() + b"\n"
        for ws in fake._watchers:
            ws.sendall(_chunk(err))
    with pytest.raises(RuntimeError, match="ERROR event"):
        next(stream)
    w.stop()


def test_token_reread_per_request(fake, tmp_path):
    """In-cluster tokens rotate (~1h): the client must send the CURRENT
    file contents, not the boot-time value."""
    tok = tmp_path / "token"
    tok.write_text("first-token")
    fake.token = "first-token"
    api = VendoredK8sApi(
        base_url=f"http://127.0.0.1:{fake.port}", token="ignored"
    )
    api._token_path = str(tok)  # the in-cluster constructor sets this
    fake._subsets = ["10.0.0.1"]
    assert api.list_namespaced_endpoints("default").items
    # rotate: both the file and the server's expectation change
    tok.write_text("second-token")
    fake.token = "second-token"
    assert api.list_namespaced_endpoints("default").items
