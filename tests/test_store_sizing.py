"""Store auto-sizing: derivation from operator budgets + the boot lint.

The r5 sweep established the footprint≍throughput law (decide cost is a
pure function of provisioned capacity, BENCH_ZIPF10M_PROFILE_r5.json);
these tests pin the sizing layer built on it: GUBER_STORE_TARGET_KEYS /
GUBER_STORE_MIB derive shapes that always satisfy the StoreConfig
invariants, and a footprint that disagrees with a declared key budget
warns at boot (or fails under GUBER_STORE_SIZE_STRICT).
"""

import logging

import pytest

from gubernator_tpu.core.store import (
    MAX_LOAD,
    SLOTS_PER_DENSE_ROW,
    StoreConfig,
    check_store_budget,
    derive_store_config,
    store_capacity,
    store_footprint_bytes,
)
from gubernator_tpu.serve.config import config_from_env


def test_derive_from_key_budget():
    # the r5-measured right-size for config 4: 10M keys -> the 512 MiB
    # shape (2^20 slots x 16 ways, load 0.60), NOT the 1 GiB table that
    # runs 1.75x slower for the same keys
    s = derive_store_config(target_keys=10_000_000)
    assert (s.rows, s.slots) == (16, 1 << 20)
    assert store_footprint_bytes(s) == 512 << 20
    # derived shapes always admit the budget under the eviction ceiling
    for keys in (1, 100, 50_000, 999_999, 3_141_592, 10_000_000):
        s = derive_store_config(target_keys=keys)
        assert keys <= store_capacity(s) * MAX_LOAD * 1.001, (keys, s)


def test_derive_from_mib():
    # exact power-of-two budgets land exactly
    s = derive_store_config(mib=512)
    assert (s.rows, s.slots) == (16, 1 << 20)
    assert store_footprint_bytes(s) == 512 << 20
    s = derive_store_config(mib=1024)
    assert store_footprint_bytes(s) == 1024 << 20
    # non-power-of-two budgets floor to the largest fitting shape
    s = derive_store_config(mib=100)
    assert store_footprint_bytes(s) <= 100 << 20
    assert store_footprint_bytes(s) == 64 << 20


def test_derive_needs_exactly_one_budget():
    with pytest.raises(ValueError):
        derive_store_config()
    with pytest.raises(ValueError):
        derive_store_config(target_keys=10, mib=10)


def test_derived_shapes_hold_store_invariants():
    """StoreConfig's own invariants (power-of-two slots, rows*slots a
    multiple of 16 for the dense 128-lane view) must hold across the
    whole derivation surface — __post_init__ asserts them, so simply
    constructing each shape is the check."""
    for rows in (1, 2, 4, 8, 16):
        for keys in (1, 7, 1000, 123_457, 10_000_000):
            s = derive_store_config(target_keys=keys, rows=rows)
            assert s.rows == rows
            assert (s.rows * s.slots) % SLOTS_PER_DENSE_ROW == 0
            assert s.slots >= SLOTS_PER_DENSE_ROW
        for mib in (1, 2, 3, 64, 513):
            s = derive_store_config(mib=mib, rows=rows)
            assert (s.rows * s.slots) % SLOTS_PER_DENSE_ROW == 0


def test_boot_derivation_from_env_knobs():
    conf = config_from_env({"GUBER_STORE_TARGET_KEYS": "10000000"})
    assert conf.store_config() == StoreConfig(rows=16, slots=1 << 20)
    # exact-only (GUBER_SKETCH=0): the whole MiB budget is the exact
    # tier, the historical derivation
    conf = config_from_env(
        {"GUBER_STORE_MIB": "1024", "GUBER_SKETCH": "0"}
    )
    assert conf.store_config() == StoreConfig(rows=16, slots=1 << 21)
    # with the sketch tier (r13, default on) the budget covers BOTH
    # tiers: the sketch's resolved footprint (1024/4 = 256 MiB) is
    # carved out and the exact tier derives from the remainder
    conf = config_from_env({"GUBER_STORE_MIB": "1024"})
    assert conf.store_config() == StoreConfig(rows=16, slots=1 << 20)
    from gubernator_tpu.core.sketches import sketch_footprint_bytes

    assert sketch_footprint_bytes(conf.sketch_config()) == 256 << 20
    # MIB wins over TARGET_KEYS for the footprint (the budget then only
    # lints); explicit slots remain the fallback
    conf = config_from_env(
        {
            "GUBER_STORE_MIB": "512",
            "GUBER_STORE_TARGET_KEYS": "10000000",
            "GUBER_SKETCH": "0",
        }
    )
    assert conf.store_config() == StoreConfig(rows=16, slots=1 << 20)
    assert config_from_env({}).store_config() == StoreConfig(
        rows=16, slots=1 << 15
    )


def test_oversized_footprint_warns_at_boot(caplog):
    """A 1 GiB table declared to serve 100k keys pays the full-table
    writeback for a ~0.3% load — the boot lint must say so."""
    conf = config_from_env(
        {
            "GUBER_STORE_MIB": "1024",
            "GUBER_STORE_TARGET_KEYS": "100000",
            "GUBER_SKETCH": "0",
        }
    )
    with caplog.at_level(logging.WARNING, "gubernator_tpu.config"):
        store = conf.store_config()
    assert store == StoreConfig(rows=16, slots=1 << 21)
    assert any("oversized" in r.message for r in caplog.records)
    # the message is actionable: it names the right-sizing knob
    msg = next(r.message for r in caplog.records if "oversized" in r.message)
    assert "GUBER_STORE_TARGET_KEYS" in msg


def test_oversized_footprint_fails_under_strict():
    conf = config_from_env(
        {
            "GUBER_STORE_MIB": "1024",
            "GUBER_STORE_TARGET_KEYS": "100000",
            "GUBER_STORE_SIZE_STRICT": "1",
        }
    )
    with pytest.raises(ValueError, match="oversized"):
        conf.store_config()


def test_undersized_footprint_warns_over_admission(caplog):
    """Key budget past the eviction ceiling of an explicit footprint ->
    over-admission warning — with the exact-only store. With the r13
    sketch tier on, undersized is the DESIGN (the tail overflows to the
    sketch fail-closed), so the same shape boots silently."""
    conf = config_from_env(
        {
            "GUBER_STORE_MIB": "16",
            "GUBER_STORE_TARGET_KEYS": "1000000",
            "GUBER_SKETCH": "0",
        }
    )
    with caplog.at_level(logging.WARNING, "gubernator_tpu.config"):
        conf.store_config()
    assert any("undersized" in r.message for r in caplog.records)
    caplog.clear()
    conf = config_from_env(
        {
            "GUBER_STORE_MIB": "16",
            "GUBER_STORE_TARGET_KEYS": "1000000",
            "GUBER_SKETCH_MIB": "4",
        }
    )
    with caplog.at_level(logging.WARNING, "gubernator_tpu.config"):
        conf.store_config()
    assert not any("undersized" in r.message for r in caplog.records)


def test_right_sized_footprint_is_silent(caplog):
    conf = config_from_env(
        {"GUBER_STORE_MIB": "512", "GUBER_STORE_TARGET_KEYS": "10000000"}
    )
    with caplog.at_level(logging.WARNING, "gubernator_tpu.config"):
        conf.store_config()
    assert not caplog.records
    # budget-derived shapes are right-sized by construction: never warn
    conf = config_from_env({"GUBER_STORE_TARGET_KEYS": "42"})
    with caplog.at_level(logging.WARNING, "gubernator_tpu.config"):
        conf.store_config()
    assert not caplog.records


def test_check_store_budget_no_budget_is_silent():
    assert check_store_budget(StoreConfig(), 0) == ""


def test_explicit_slots_pin_is_linted_not_overridden(caplog):
    """An EXPLICIT GUBER_STORE_SLOTS pin plus a key budget keeps the
    pinned geometry and lints it — deriving over a deliberate pin would
    silently change the HBM footprint the operator chose."""
    conf = config_from_env(
        {
            "GUBER_STORE_SLOTS": "2048",
            "GUBER_STORE_TARGET_KEYS": "10000000",
            "GUBER_SKETCH": "0",  # exact-only: the undersize lint fires
        }
    )
    with caplog.at_level(logging.WARNING, "gubernator_tpu.config"):
        store = conf.store_config()
    assert store == StoreConfig(rows=16, slots=2048)  # pin kept
    assert any("undersized" in r.message for r in caplog.records)
    # without the explicit pin the same budget derives the right size
    conf = config_from_env({"GUBER_STORE_TARGET_KEYS": "10000000"})
    assert conf.store_config() == StoreConfig(rows=16, slots=1 << 20)


def test_directly_constructed_config_keeps_slot_pin(caplog):
    """Library embedders construct ServerConfig without config_from_env;
    a non-default store_slots there is a pin too — linted, never derived
    over."""
    from gubernator_tpu.serve.config import ServerConfig

    conf = ServerConfig(
        store_slots=1 << 11, store_target_keys=10_000_000, sketch=False
    )
    with caplog.at_level(logging.WARNING, "gubernator_tpu.config"):
        store = conf.store_config()
    assert store == StoreConfig(rows=16, slots=1 << 11)
    assert any("undersized" in r.message for r in caplog.records)
    # default slots + a budget still derives (nothing was pinned)
    assert ServerConfig(store_target_keys=10_000_000).store_config() == (
        StoreConfig(rows=16, slots=1 << 20)
    )
