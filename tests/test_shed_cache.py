"""Over-limit shed cache (r10): differential identity + invalidation.

The shed contract under test (serve/shedcache.py): with the cache ON,
every response is byte-identical to the cache-OFF pipeline — the shed
only answers requests whose verdict is a frozen token-bucket refusal
the device would echo verbatim. The suites here pin:

- randomized differential identity ON vs OFF over the exact backend
  AND the device (tpu-on-cpu) backend: mixed token/leaky algorithms,
  duplicate keys per batch, peeks, oversized hits, mid-window
  limit/duration changes, and clock advances across reset boundaries
  (a shared fake clock drives both pipelines so reset_time compares
  exactly);
- peeks (hits=0) bypass the shed entirely;
- the reset_time expiry boundary: the first post-reset hit reaches the
  device (and recreates the window there);
- GLOBAL-update invalidation: an UpdatePeerGlobals install purges its
  keys so a replica reset is never shadowed by a stale verdict;
- owned-GLOBAL sheds preserve the broadcast side effect (queue_update);
- bridge-tier shed under windowed multi-frame load (GEB7): shed items
  never reach the batcher, responses stitch back in frame order, and
  the `shed` stage keeps the frame-coverage contract;
- the engine reset-generation clears the cache.
"""

import asyncio
import struct

import numpy as np
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    Status,
    millisecond_now,
)
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve.backends import ExactBackend, TpuBackend
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import Instance
from gubernator_tpu.serve.shedcache import ShedCache

T0 = 1_700_000_000_000
ADDR = "127.0.0.1:7971"


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self) -> int:
        return self.t


# -- ShedCache unit gates ---------------------------------------------------


def test_lookup_gates_and_expiry():
    clock = FakeClock()
    c = ShedCache(8, now_fn=clock)
    c._observe_one(42, 1, 10, 1000, 0, int(Status.OVER_LIMIT), 10, 0,
                   clock.t + 500, clock.t)
    assert len(c) == 1
    r = RateLimitReq(name="n", unique_key="k", hits=1, limit=10,
                     duration=1000)
    assert c.lookup_resp(42, r).reset_time == clock.t + 500
    # param mismatch is a miss, not a drop
    r2 = RateLimitReq(name="n", unique_key="k", hits=1, limit=11,
                      duration=1000)
    assert c.lookup_resp(42, r2) is None and len(c) == 1
    # peek and leaky bypass (not even a lookup)
    lk = c.lookups
    assert c.lookup_resp(
        42, RateLimitReq(name="n", unique_key="k", hits=0, limit=10,
                         duration=1000)
    ) is None
    assert c.lookup_resp(
        42, RateLimitReq(name="n", unique_key="k", hits=1, limit=10,
                         duration=1000,
                         algorithm=Algorithm.LEAKY_BUCKET)
    ) is None
    assert c.lookups == lk
    # expiry boundary: at now == reset_time the entry is dead (the
    # first post-reset hit must reach the device)
    clock.t += 500
    assert c.lookup_resp(42, r) is None
    assert len(c) == 0


def test_r15_algorithms_never_shed():
    """r15 interplay audit (core/algorithms.py SHEDDABLE_ALGOS): a
    sliding-window blend decays and a GCRA TAT drains every
    millisecond, so their OVER verdicts are never provably current —
    they must neither consult nor populate the shed cache, and a
    stale token entry must never answer one of their requests."""
    from gubernator_tpu.core.algorithms import sheddable

    assert not sheddable(int(Algorithm.SLIDING_WINDOW))
    assert not sheddable(int(Algorithm.GCRA))

    clock = FakeClock()
    c = ShedCache(8, now_fn=clock)
    c._observe_one(42, 1, 10, 1000, 0, int(Status.OVER_LIMIT), 10, 0,
                   clock.t + 500, clock.t)
    assert len(c) == 1
    # a cached token verdict never answers a sliding/GCRA request for
    # the same fingerprint (not even a lookup)
    lk = c.lookups
    for algo in (Algorithm.SLIDING_WINDOW, Algorithm.GCRA):
        assert c.lookup_resp(
            42, RateLimitReq(name="n", unique_key="k", hits=1,
                             limit=10, duration=1000, algorithm=algo)
        ) is None
    assert c.lookups == lk
    # the bridge array screen is equally gated: a GCRA row over a
    # cached fingerprint does not shed
    for algo in (2, 3):
        fields = dict(
            key_hash=np.array([42], np.uint64),
            hits=np.array([1], np.int64),
            limit=np.array([10], np.int64),
            duration=np.array([1000], np.int64),
            algo=np.array([algo], np.int32),
        )
        assert c.screen_fields(fields, clock.t) is None
    # observing a sliding/GCRA response DROPS the stale token entry
    # (algorithm switch recreates the window, like leaky)...
    c._observe_one(42, 1, 10, 1000, int(Algorithm.GCRA),
                   int(Status.OVER_LIMIT), 10, 0, clock.t + 500,
                   clock.t)
    assert 42 not in c._entries
    # ...and never populates one of its own
    for algo in (2, 3):
        c._observe_one(7, 1, 10, 1000, algo, int(Status.OVER_LIMIT),
                       10, 0, clock.t + 500, clock.t)
        assert 7 not in c._entries


def test_lru_bound_and_observe_drop():
    clock = FakeClock()
    c = ShedCache(4, now_fn=clock)
    for h in range(6):
        c._observe_one(h, 1, 5, 1000, 0, int(Status.OVER_LIMIT), 5, 0,
                       clock.t + 9999, clock.t)
    assert len(c) == 4  # bounded; oldest evicted
    assert 0 not in c._entries and 5 in c._entries
    # an under-limit response for a cached fingerprint drops it
    c._observe_one(5, 1, 5, 1000, 0, int(Status.UNDER_LIMIT), 5, 3,
                   clock.t + 9999, clock.t)
    assert 5 not in c._entries
    # a leaky request for a cached fingerprint drops it (algo switch)
    c._observe_one(4, 1, 5, 1000, 1, int(Status.UNDER_LIMIT), 5, 4, 0,
                   clock.t)
    assert 4 not in c._entries


def test_observe_confirmation_vs_contradiction():
    """The device answers an existing window's hits with the STORED
    limit, so a param-mismatched request's response ECHOES the cached
    window — it must confirm the entry, not drop it (mixed-param
    traffic would otherwise thrash the cache on the hottest keys).
    Only a response contradicting the cached window drops it."""
    clock = FakeClock()
    c = ShedCache(8, now_fn=clock)
    reset = clock.t + 9999
    c._observe_one(9, 1, 10, 1000, 0, int(Status.OVER_LIMIT), 10, 0,
                   reset, clock.t)
    # req_limit=20 mismatches, but the response echoes the stored
    # window (limit 10, same reset): keep
    c._observe_one(9, 1, 20, 1000, 0, int(Status.OVER_LIMIT), 10, 0,
                   reset, clock.t)
    assert c._entries[9] == (10, 1000, reset)
    # a different reset means the window was recreated: drop
    c._observe_one(9, 1, 20, 1000, 0, int(Status.OVER_LIMIT), 10, 0,
                   reset + 5, clock.t)
    assert 9 not in c._entries


def test_generation_clears():
    gen = [0]
    c = ShedCache(8, now_fn=FakeClock(), generation_fn=lambda: gen[0])
    c._observe_one(1, 1, 5, 1000, 0, int(Status.OVER_LIMIT), 5, 0,
                   T0 + 9999, T0)
    c.refresh_generation()
    assert len(c) == 1
    gen[0] += 1  # engine store wiped
    c.refresh_generation()
    assert len(c) == 0


# -- instance harness -------------------------------------------------------


async def _mk_instance(backend, shed: bool) -> Instance:
    conf = ServerConfig(
        grpc_address=ADDR, advertise_address=ADDR, shed_cache=shed
    )
    inst = Instance(conf, backend)
    inst.start()
    await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
    return inst


def _pin_clock(monkeypatch, clock):
    """Route every now() the serving pipeline reads through the fake
    clock: the oracle (exact backend), the engine module (device
    backends' module-level import), and api.types (the backends'
    call-time local imports)."""
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


def _assert_same(a, b, ctx):
    assert (
        a.status, a.limit, a.remaining, a.reset_time, a.error
    ) == (
        b.status, b.limit, b.remaining, b.reset_time, b.error
    ), (ctx, a, b)


def _fuzz_stream(rng, keys, steps):
    """Random request batches: mixed algorithms (pinned per key so the
    streams stay meaningful), duplicate keys, peeks, oversized hits,
    mid-window limit/duration changes, clock advances across resets."""
    for step in range(steps):
        n = int(rng.integers(1, 7))
        batch = []
        for _ in range(n):
            k = int(rng.integers(len(keys)))
            batch.append(
                RateLimitReq(
                    name="shedfuzz",
                    unique_key=keys[k],
                    hits=int(rng.choice([0, 1, 1, 1, 2, 9])),
                    limit=int(rng.choice([1, 1, 2, 3, 50])),
                    duration=int(rng.choice([400, 2000, 60_000])),
                    algorithm=Algorithm(k % 2),
                )
            )
        yield step, batch, int(rng.choice([0, 0, 1, 7, 150, 500, 2500]))


@pytest.mark.parametrize("seed", [3, 11])
def test_differential_identity_fuzz_exact(monkeypatch, seed):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    async def run():
        on = await _mk_instance(ExactBackend(10_000), shed=True)
        off = await _mk_instance(ExactBackend(10_000), shed=False)
        on.shed.now_fn = clock
        try:
            rng = np.random.default_rng(seed)
            keys = [f"k{i}" for i in range(14)]
            for step, batch, dt in _fuzz_stream(rng, keys, 350):
                clock.t += dt
                a = await on.get_rate_limits(batch)
                b = await off.get_rate_limits(batch)
                for x, y, r in zip(a, b, batch):
                    _assert_same(x, y, (step, r))
            assert on.shed.hits > 0, "fuzz never exercised a shed"
        finally:
            await on.stop()
            await off.stop()

    asyncio.run(run())


def test_differential_identity_fuzz_device(monkeypatch):
    """Same identity contract across the DEVICE pipeline (tpu backend
    on cpu): instance -> batcher -> arrival prep -> merged submit ->
    kernel, shed ON vs OFF."""
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    def be():
        return TpuBackend(
            StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
        )

    async def run():
        on = await _mk_instance(be(), shed=True)
        off = await _mk_instance(be(), shed=False)
        on.shed.now_fn = clock
        try:
            rng = np.random.default_rng(5)
            keys = [f"d{i}" for i in range(12)]
            for step, batch, dt in _fuzz_stream(rng, keys, 120):
                clock.t += dt
                a = await on.get_rate_limits(batch)
                b = await off.get_rate_limits(batch)
                for x, y, r in zip(a, b, batch):
                    _assert_same(x, y, (step, r))
            assert on.shed.hits > 0, "fuzz never exercised a shed"
        finally:
            await on.stop()
            await off.stop()

    asyncio.run(run())


def test_peek_bypass_and_post_reset_hit_reaches_device(monkeypatch):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    async def run():
        inst = await _mk_instance(ExactBackend(1000), shed=True)
        inst.shed.now_fn = clock
        try:
            def req(hits=1):
                return RateLimitReq(
                    name="pb", unique_key="x", hits=hits, limit=1,
                    duration=1000,
                )

            r1 = (await inst.get_rate_limits([req()]))[0]
            assert r1.status == Status.UNDER_LIMIT  # creation, rem 0
            r2 = (await inst.get_rate_limits([req()]))[0]
            assert r2.status == Status.OVER_LIMIT  # frozen; now cached
            assert len(inst.shed) == 1
            r3 = (await inst.get_rate_limits([req()]))[0]
            assert inst.shed.hits == 1  # shed
            _assert_same(r2, r3, "frozen verdict")
            # a peek bypasses the shed but gets the same frozen answer
            lk = inst.shed.lookups
            r4 = (await inst.get_rate_limits([req(hits=0)]))[0]
            assert inst.shed.lookups == lk
            _assert_same(r2, r4, "peek")
            # cross the reset boundary: the next hit must reach the
            # device and recreate the window there
            clock.t = r2.reset_time + 1
            hits_before = inst.shed.hits
            r5 = (await inst.get_rate_limits([req()]))[0]
            assert inst.shed.hits == hits_before  # not shed
            assert r5.status == Status.UNDER_LIMIT  # fresh window
            assert r5.reset_time == clock.t + 1000
        finally:
            await inst.stop()

    asyncio.run(run())


def test_global_update_purges_cached_verdict(monkeypatch):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    async def run():
        on = await _mk_instance(ExactBackend(1000), shed=True)
        off = await _mk_instance(ExactBackend(1000), shed=False)
        on.shed.now_fn = clock
        try:
            def req():
                return RateLimitReq(
                    name="g", unique_key="y", hits=1, limit=1,
                    duration=60_000,
                )

            for inst in (on, off):
                await inst.get_rate_limits([req(), req()])
            assert len(on.shed) == 1
            # owner-side reset arrives as a replica install: the shed
            # entry must die with it, or GLOBAL mode would keep
            # serving the stale refusal
            key = req().hash_key()

            def fresh():
                # one object per install: the exact backend stores the
                # replica object itself and mutates it in place
                return RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=1, remaining=1,
                    reset_time=clock.t + 60_000,
                )

            for inst in (on, off):
                await inst.update_peer_globals([(key, fresh())])
            assert len(on.shed) == 0
            a = (await on.get_rate_limits([req()]))[0]
            b = (await off.get_rate_limits([req()]))[0]
            _assert_same(a, b, "post-install identity")
        finally:
            await on.stop()
            await off.stop()

    asyncio.run(run())


def test_owned_global_shed_preserves_broadcast_side_effect(monkeypatch):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    async def run():
        inst = await _mk_instance(ExactBackend(1000), shed=True)
        inst.shed.now_fn = clock
        queued = []
        inst.global_mgr.queue_update = lambda r: queued.append(
            r.hash_key()
        )
        try:
            def req():
                return RateLimitReq(
                    name="gb", unique_key="z", hits=1, limit=1,
                    duration=60_000, behavior=Behavior.GLOBAL,
                )

            await inst.get_rate_limits([req(), req()])
            n_before = len(queued)
            assert n_before > 0
            r = (await inst.get_rate_limits([req()]))[0]
            assert inst.shed.hits >= 1 and r.status == Status.OVER_LIMIT
            # the shed answer still queued the owner's status broadcast
            assert len(queued) == n_before + 1
        finally:
            await inst.stop()

    asyncio.run(run())


def test_peer_serve_screen_identity(monkeypatch):
    """Owner-side forwarded batches (get_peer_rate_limits) screen the
    same cache: identity with the unscreened pipeline, shed hits
    recorded."""
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    async def run():
        on = await _mk_instance(ExactBackend(1000), shed=True)
        off = await _mk_instance(ExactBackend(1000), shed=False)
        on.shed.now_fn = clock
        try:
            reqs = [
                RateLimitReq(name="ps", unique_key="w", hits=1,
                             limit=1, duration=60_000)
                for _ in range(3)
            ]
            for inst in (on, off):
                await inst.get_peer_rate_limits(reqs)
            a = await on.get_peer_rate_limits(reqs)
            b = await off.get_peer_rate_limits(reqs)
            for x, y in zip(a, b):
                _assert_same(x, y, "peer serve")
            assert on.shed.hits >= 3
        finally:
            await on.stop()
            await off.stop()

    asyncio.run(run())


# -- bridge tier ------------------------------------------------------------


def _wfast(fid, rec, ring_hash):
    from gubernator_tpu.serve.edge_bridge import MAGIC_WFAST_REQ

    payload = rec.tobytes()
    return (
        struct.pack("<II", MAGIC_WFAST_REQ, len(rec))
        + struct.pack("<IIQ", fid, ring_hash, 0)
        + struct.pack("<I", len(payload))
        + payload
    )


async def _read_wfast_resp(reader):
    from gubernator_tpu.serve.edge_bridge import (
        MAGIC_WFAST_RESP,
        _fast_dtypes,
    )

    magic, n = struct.unpack("<II", await reader.readexactly(8))
    assert magic == MAGIC_WFAST_RESP, hex(magic)
    (fid,) = struct.unpack("<I", await reader.readexactly(4))
    _, resp_dt = _fast_dtypes()
    rec = np.frombuffer(
        await reader.readexactly(n * resp_dt.itemsize), dtype=resp_dt
    )
    return fid, rec


def test_bridge_tier_shed_windowed_frames():
    """GEB7 frames screen the shed cache before the batcher: a frame of
    frozen refusals is answered without a device trip, mixed frames
    stitch shed + device rows in order, multiple frames stay in flight,
    and the `shed` stage appears in the clock."""
    from gubernator_tpu.serve.edge_bridge import EdgeBridge
    from gubernator_tpu.serve.stages import STAGES

    path = "/tmp/guber-shed-bridge-test.sock"

    async def run():
        backend = TpuBackend(
            StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
        )
        inst = await _mk_instance(backend, shed=True)
        bridge = EdgeBridge(inst, path)
        await bridge.start()
        try:
            from tests.test_edge_bridge import _read_hello

            reader, writer = await asyncio.open_unix_connection(path)
            _flags, rhash, _nodes = await _read_hello(reader)

            from gubernator_tpu.serve.edge_bridge import _fast_dtypes

            req_dt, _ = _fast_dtypes()

            def recs(key_hashes, limit=1):
                rec = np.zeros(len(key_hashes), req_dt)
                rec["key_hash"] = key_hashes
                rec["hits"] = 1
                rec["limit"] = limit
                rec["duration"] = 60_000
                return rec

            # frame 1: duplicate key drains the window; follower rows
            # come back (OVER, remaining 0) and populate the cache
            writer.write(_wfast(1, recs([7, 7, 7, 7]), rhash))
            await writer.drain()
            fid, rec = await asyncio.wait_for(_read_wfast_resp(reader), 30)
            assert fid == 1
            assert rec["status"].tolist() == [0, 1, 1, 1]
            frozen_reset = int(rec["reset_time"][3])
            assert len(inst.shed) == 1

            # frame 2: fully shed — no device batch happens
            batches_before = backend.stats()["batches"]
            hits_before = inst.shed.hits
            writer.write(_wfast(2, recs([7, 7, 7]), rhash))
            await writer.drain()
            fid, rec = await asyncio.wait_for(_read_wfast_resp(reader), 30)
            assert fid == 2
            assert rec["status"].tolist() == [1, 1, 1]
            assert rec["remaining"].tolist() == [0, 0, 0]
            assert rec["reset_time"].tolist() == [frozen_reset] * 3
            assert inst.shed.hits == hits_before + 3
            assert backend.stats()["batches"] == batches_before

            # frame 3: mixed shed + residue rows stitch back in order
            writer.write(_wfast(3, recs([7, 8, 7, 8]), rhash))
            await writer.drain()
            fid, rec = await asyncio.wait_for(_read_wfast_resp(reader), 30)
            assert fid == 3
            # key 7 rows frozen; key 8 rows are a fresh creation group
            # (leader UNDER rem 0, follower OVER rem 0)
            assert rec["status"].tolist() == [1, 0, 1, 1]
            assert rec["reset_time"][0] == frozen_reset
            assert rec["reset_time"][2] == frozen_reset
            assert backend.stats()["batches"] == batches_before + 1

            # two frames in flight, fully shed: ids match out of the
            # window regardless of completion order
            writer.write(_wfast(4, recs([7, 7]), rhash))
            writer.write(_wfast(5, recs([8, 8]), rhash))
            await writer.drain()
            got = {}
            for _ in range(2):
                fid, rec = await asyncio.wait_for(
                    _read_wfast_resp(reader), 30
                )
                got[fid] = rec["status"].tolist()
            assert got[4] == [1, 1] and got[5] == [1, 1]

            snap = STAGES.snapshot()
            assert "shed" in snap["stages"]
            assert "shed" in snap["per_frame_stages"]
            writer.close()
        finally:
            await bridge.stop()
            await inst.stop()

    asyncio.run(run())


def test_bridge_string_fold_shed():
    """The GEB1 string fold rides the same screen: the second frame for
    a frozen key sheds, and the response stays a well-formed GEB3."""
    from gubernator_tpu.serve.edge_bridge import MAGIC_RESP, EdgeBridge

    path = "/tmp/guber-shed-fold-test.sock"

    async def run():
        backend = TpuBackend(
            StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
        )
        inst = await _mk_instance(backend, shed=True)
        bridge = EdgeBridge(inst, path)
        await bridge.start()
        try:
            from tests.test_edge_bridge import (
                _frame,
                _item,
                _read_hello,
            )

            reader, writer = await asyncio.open_unix_connection(path)
            await _read_hello(reader)

            async def roundtrip():
                writer.write(_frame([
                    _item(b"fold", b"hot", hits=1, limit=1,
                          duration=60_000),
                    _item(b"fold", b"hot", hits=1, limit=1,
                          duration=60_000),
                ]))
                await writer.drain()
                magic, n = struct.unpack(
                    "<II", await reader.readexactly(8)
                )
                assert magic == MAGIC_RESP and n == 2
                out = []
                for _ in range(n):
                    status, limit, remaining, reset = struct.unpack(
                        "<Bqqq", await reader.readexactly(25)
                    )
                    (elen,) = struct.unpack(
                        "<H", await reader.readexactly(2)
                    )
                    await reader.readexactly(elen)
                    (olen,) = struct.unpack(
                        "<H", await reader.readexactly(2)
                    )
                    await reader.readexactly(olen)
                    out.append((status, limit, remaining, reset))
                return out

            first = await asyncio.wait_for(roundtrip(), 30)
            assert [s for s, *_ in first] == [0, 1]
            assert len(inst.shed) == 1
            hits = inst.shed.hits
            second = await asyncio.wait_for(roundtrip(), 30)
            assert [s for s, *_ in second] == [1, 1]
            assert [r for *_, r in second] == [first[1][3]] * 2
            assert inst.shed.hits == hits + 2
            writer.close()
        finally:
            await bridge.stop()
            await inst.stop()

    asyncio.run(run())


def test_engine_reset_generation_clears_instance_cache(monkeypatch):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    async def run():
        backend = TpuBackend(
            StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
        )
        inst = await _mk_instance(backend, shed=True)
        inst.shed.now_fn = clock
        try:
            def req():
                return RateLimitReq(
                    name="rg", unique_key="q", hits=1, limit=1,
                    duration=60_000,
                )

            await inst.get_rate_limits([req(), req()])
            assert len(inst.shed) == 1
            backend.engine.reset()  # store wiped (clock-jump path)
            r = (await inst.get_rate_limits([req()]))[0]
            # fresh store: the request recreated the window instead of
            # being answered from a stale cached refusal
            assert r.status == Status.UNDER_LIMIT
        finally:
            await inst.stop()

    asyncio.run(run())
