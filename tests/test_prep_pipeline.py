"""Arrival-time host-prep pipeline (r9): merge-combine equivalence.

The serving contract under test: a device batch built by MERGING the
caller groups' pre-sorted runs (arrival-time prep, serve/prep.py +
engine decide_submit_presorted) is byte-identical — padded request
fields, duplicate-key group structure, and response permutation — to
the flush-time concat + full-argsort path it replaces, across mixed
request-object/array groups, duplicate keys, GNP flags, saturating
values, empty groups, and carry overflow; and that arrival-time vs
flush-time prep produce identical decisions, responses slice back to
the right callers, and stop() mid-prep strands no futures.
"""

import asyncio
import threading
import time

import numpy as np
import pytest

from gubernator_tpu.api.types import Algorithm, RateLimitReq
from gubernator_tpu.core.engine import (
    build_presorted_request,
    pad_request_sorted,
    prep_run_single,
)
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.parallel.sharded import (
    build_presorted_sharded,
    pad_request_sharded,
    prep_run_sharded,
    sub_batch_ladder,
)
from gubernator_tpu.serve.backends import TpuBackend
from gubernator_tpu.serve.batcher import DeviceBatcher
from gubernator_tpu.serve.prep import merge_runs, merge_sorted_runs

BUCKETS = (64, 256, 1024)
SLOTS = 1 << 10


def _rand_group(rng, n, dup_pool=None):
    """One caller group's array fields: duplicate-heavy keys, values
    spanning the int32 saturation boundaries, random GNP flags."""
    if dup_pool is None:
        dup_pool = rng.integers(1, 2**63, max(2 * n, 4), np.int64).astype(
            np.uint64
        )
    return dict(
        key_hash=rng.choice(dup_pool, n),
        hits=rng.integers(-(2**40), 2**40, n),
        limit=rng.integers(0, 2**40, n),
        duration=rng.integers(-5, 2**40, n),
        algo=rng.integers(0, 2, n).astype(np.int32),
        gnp=rng.random(n) < 0.3,
    )


def _concat(groups):
    return {
        k: np.concatenate([g[k] for g in groups])
        for k in ("key_hash", "hits", "limit", "duration", "algo", "gnp")
    }


def _assert_same(a, b, what):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype, (what, a.dtype, b.dtype)
    assert a.shape == b.shape, (what, a.shape, b.shape)
    assert np.array_equal(a, b), what


def test_merge_take_equals_stable_argsort():
    """The k-way merge's take permutation IS np.argsort(concat,
    kind='stable') of the pre-sorted runs — including empty runs and
    heavy cross-run ties."""
    rng = np.random.default_rng(0xA11)
    for trial in range(50):
        k = int(rng.integers(1, 9))
        runs = [
            np.sort(
                rng.integers(
                    0, 30, int(rng.integers(0, 40)), dtype=np.uint64
                )
            )
            for _ in range(k)
        ]
        skey, take = merge_sorted_runs(runs)
        cat = np.concatenate(runs) if runs else np.empty(0, np.uint64)
        _assert_same(take, np.argsort(cat, kind="stable"), "take")
        _assert_same(skey, cat[take], "skey")


def _merged_single(groups, force_numpy=False):
    runs = [prep_run_single(g, SLOTS) for g in groups]
    if force_numpy:
        import gubernator_tpu.serve.prep as prep_mod

        real = prep_mod._hn
        prep_mod._hn = None
        try:
            m = merge_runs(runs)
        finally:
            prep_mod._hn = real
    else:
        m = merge_runs(runs)
    n = int(sum(g["key_hash"].shape[0] for g in groups))
    req, grp, B = build_presorted_request(
        sorted(BUCKETS), m["fields"], m["skey"], n
    )
    return m, req, grp, B, n


@pytest.mark.parametrize("force_numpy", [False, True])
def test_merged_fields_byte_identical_single_device(force_numpy):
    """Merge-combined batches produce byte-identical padded request
    fields, groups, and order vs pad_request_sorted's concat+argsort
    path, across randomized mixed group counts/sizes — on BOTH the
    fused native merge (guber_merge_runs) and the numpy searchsorted
    fallback. Also pins the engine-level fused path (merge_prepped,
    which pads + derives groups natively in the same pass)."""
    from gubernator_tpu.core.engine import TpuEngine

    eng = TpuEngine(StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS)
    rng = np.random.default_rng(0xBEEF)
    for trial in range(15):
        k = int(rng.integers(1, 7))
        pool = rng.integers(1, 2**63, 64, np.int64).astype(np.uint64)
        groups = [
            _rand_group(rng, int(rng.integers(1, 200)), pool)
            for _ in range(k)
        ]
        cat = _concat(groups)
        req_ref, order_ref, grp_ref = pad_request_sorted(
            sorted(BUCKETS), SLOTS, cat["key_hash"], cat["hits"],
            cat["limit"], cat["duration"], cat["algo"], cat["gnp"],
            with_groups=True,
        )
        m, req, grp, B, n = _merged_single(groups, force_numpy)
        for f in req._fields:
            _assert_same(
                getattr(req, f), getattr(req_ref, f), f"req.{f}"
            )
        for f in grp._fields:
            _assert_same(
                getattr(grp, f), getattr(grp_ref, f), f"groups.{f}"
            )
        _assert_same(m["order"], order_ref[:n], "order")
        if not force_numpy:
            merged = eng.merge_prepped(
                [prep_run_single(g, SLOTS) for g in groups]
            )
            for f in req_ref._fields:
                _assert_same(
                    getattr(merged["req"], f), getattr(req_ref, f),
                    f"merge_prepped req.{f}",
                )
            for f in grp_ref._fields:
                _assert_same(
                    getattr(merged["groups"], f), getattr(grp_ref, f),
                    f"merge_prepped groups.{f}",
                )
            _assert_same(merged["order"], order_ref, "merge_prepped order")


def test_merged_fields_byte_identical_sharded():
    """Mesh sibling: merged runs through build_presorted_sharded match
    pad_request_sharded's output exactly (per-shard padded fields,
    local group structure, take_idx, order)."""
    rng = np.random.default_rng(0xFACE)
    sub = sub_batch_ladder(BUCKETS)
    for n_shards in (1, 3, 4):
        for trial in range(10):
            k = int(rng.integers(1, 6))
            pool = rng.integers(1, 2**63, 48, np.int64).astype(np.uint64)
            groups = [
                _rand_group(rng, int(rng.integers(1, 150)), pool)
                for _ in range(k)
            ]
            cat = _concat(groups)
            req_ref, order_ref, take_ref, grp_ref = pad_request_sharded(
                sub, SLOTS, n_shards, cat["key_hash"], cat["hits"],
                cat["limit"], cat["duration"], cat["algo"], cat["gnp"],
                with_groups=True,
            )
            runs = [
                prep_run_sharded(g, SLOTS, n_shards) for g in groups
            ]
            m = merge_runs(runs)
            req, take, grp, B_sub = build_presorted_sharded(
                sub, SLOTS, n_shards, m["fields"], m["skey"],
                m["counts"],
            )
            for f in req._fields:
                _assert_same(
                    getattr(req, f), getattr(req_ref, f), f"req.{f}"
                )
            for f in grp._fields:
                _assert_same(
                    getattr(grp, f), getattr(grp_ref, f), f"groups.{f}"
                )
            _assert_same(m["order"], order_ref, "order")
            _assert_same(take, take_ref, "take_idx")


def test_engine_presorted_matches_concat_argsort_end_to_end():
    """Twin engines, same batches, same clock: one decides via the
    flush-time array path (decide_submit_arrays), the other via
    arrival-prep + merge (decide_submit_presorted). Every response
    array — and therefore every store mutation — must be identical."""
    be_a = TpuBackend(StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS)
    be_b = TpuBackend(StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS)
    rng = np.random.default_rng(0xD0)
    now = 1_700_000_000_000
    for step in range(8):
        k = int(rng.integers(1, 5))
        pool = rng.integers(1, 2**63, 32, np.int64).astype(np.uint64)
        groups = [
            _rand_group(rng, int(rng.integers(1, 120)), pool)
            for _ in range(k)
        ]
        cat = _concat(groups)
        ra = be_a.decide_wait_arrays(
            be_a.decide_submit_arrays(dict(cat), now=now)
        )
        merged = be_b.merge_prepped(
            [be_b.prep_group(dict(g)) for g in groups]
        )
        rb = be_b.decide_wait_arrays(
            be_b.decide_submit_merged(merged, now=now)
        )
        for name, a, b in zip(
            ("status", "limit", "remaining", "reset"), ra, rb
        ):
            _assert_same(a, b, f"step {step} {name}")
        now += 1000


def _mk_reqs(tag, n, limit=1000):
    return [
        RateLimitReq(
            name="prep", unique_key=f"{tag}-{i}", hits=1,
            limit=limit + i, duration=60_000,
            algorithm=Algorithm.TOKEN_BUCKET,
        )
        for i in range(n)
    ]


def _run(coro):
    return asyncio.run(coro)


def test_batcher_merged_slicing_mixed_groups():
    """One flush of mixed object/array groups through the merged path:
    every caller gets exactly its own rows back (limit echoes input,
    so slicing errors are visible per row). Also exercises carry
    overflow: the last group exceeds batch_limit and ships in a second
    batch."""

    async def scenario():
        be = TpuBackend(
            StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS
        )
        b = DeviceBatcher(
            be, batch_wait=0, batch_limit=256, prep_at_arrival=True
        )
        assert b._prep_ok
        # enqueue BEFORE starting the flusher: one deterministic batch
        # composition (plus the carry group that overflows it)
        fields = dict(
            key_hash=(
                np.arange(1, 41, dtype=np.uint64) << np.uint64(32)
            ),
            hits=np.ones(40, np.int64),
            limit=np.arange(5000, 5040, dtype=np.int64),
            duration=np.full(40, 60_000, np.int64),
            algo=np.zeros(40, np.int32),
        )
        tasks = [
            asyncio.ensure_future(b.decide(_mk_reqs("a", 30), [False] * 30)),
            asyncio.ensure_future(b.decide_arrays(dict(fields))),
            asyncio.ensure_future(
                b.decide(
                    _mk_reqs("g", 20, limit=77), [True] * 20
                )
            ),
            # 240 rows: pushes past batch_limit=256 -> parked (carry)
            asyncio.ensure_future(
                b.decide(_mk_reqs("c", 240, limit=9000), [False] * 240)
            ),
        ]
        await asyncio.sleep(0)  # everything enqueued
        b.start()
        r_obj, r_arr, r_gnp, r_carry = await asyncio.gather(*tasks)
        assert [r.limit for r in r_obj] == [1000 + i for i in range(30)]
        assert list(r_arr[1]) == list(range(5000, 5040))
        assert [r.limit for r in r_gnp] == [77 + i for i in range(20)]
        assert [r.limit for r in r_carry] == [
            9000 + i for i in range(240)
        ]
        await b.stop()

    _run(scenario())


def test_arrival_vs_flush_prep_identical_decisions():
    """Same traffic, same pinned clock, twin backends: arrival-time
    prep ON vs the flush-time fallback (prep futures suppressed) must
    produce identical responses — prepping earlier changes WHERE the
    work runs, never the result."""

    async def run_once(suppress_kick):
        be = TpuBackend(
            StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS
        )
        b = DeviceBatcher(
            be, batch_wait=0, batch_limit=1024, prep_at_arrival=True
        )
        if suppress_kick:
            b._kick_prep = lambda *a, **k: None
        tasks = [
            asyncio.ensure_future(
                b.decide(_mk_reqs(f"t{g}", 50), [g % 2 == 0] * 50)
            )
            for g in range(4)
        ]
        await asyncio.sleep(0)
        b.start()
        out = await asyncio.gather(*tasks)
        await b.stop()
        return [
            (r.status, r.limit, r.remaining) for rs in out for r in rs
        ]

    import gubernator_tpu.api.types as types

    real_now = types.millisecond_now
    types.millisecond_now = lambda: 1_700_000_000_000
    try:
        a = _run(run_once(False))
        f = _run(run_once(True))
    finally:
        types.millisecond_now = real_now
    assert a == f


def test_stop_mid_prep_strands_no_futures():
    """stop() while arrival preps are still running/queued: every
    caller future resolves (with an error), nothing hangs, and the
    prep pool is shut down."""

    async def scenario():
        be = TpuBackend(
            StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS
        )
        b = DeviceBatcher(
            be, batch_wait=0.05, batch_limit=1024,
            prep_at_arrival=True, prep_threads=1,
        )
        real_prep = be.prep_group
        started = threading.Event()

        def slow_prep(fields):
            started.set()
            time.sleep(0.4)
            return real_prep(fields)

        be.prep_group = slow_prep
        b.start()
        fields = dict(
            key_hash=np.arange(1, 9, dtype=np.uint64) << np.uint64(32),
            hits=np.ones(8, np.int64),
            limit=np.full(8, 100, np.int64),
            duration=np.full(8, 60_000, np.int64),
            algo=np.zeros(8, np.int32),
        )
        tasks = [
            asyncio.ensure_future(b.decide_arrays(dict(fields)))
            for _ in range(4)
        ]
        await asyncio.sleep(0)
        assert started.wait(timeout=5)
        t0 = time.monotonic()
        await b.stop()
        done = await asyncio.gather(*tasks, return_exceptions=True)
        assert time.monotonic() - t0 < 5.0
        # every caller resolved; the batch the stop interrupted fails
        # with the batcher's stop error, none hang or leak
        for r in done:
            assert isinstance(r, (Exception, tuple)), r
        assert b._prep_pool._shutdown

    _run(scenario())


def test_decide_arrays_empty_group_dtype_contract():
    """The documented empty-group contract: four EMPTY int64 arrays,
    resolved synchronously, numpy imported at module level (not per
    call)."""

    async def scenario():
        be = TpuBackend(
            StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS
        )
        b = DeviceBatcher(be, batch_wait=0, batch_limit=64)
        empty = dict(
            key_hash=np.empty(0, np.uint64),
            hits=np.empty(0, np.int64),
            limit=np.empty(0, np.int64),
            duration=np.empty(0, np.int64),
            algo=np.empty(0, np.int32),
        )
        # resolves without the flusher even running (and after stop)
        out = await b.decide_arrays(empty)
        assert len(out) == 4
        for a in out:
            assert a.shape == (0,) and a.dtype == np.int64
        await b.stop()

    _run(scenario())
    import ast
    import inspect

    import gubernator_tpu.serve.batcher as batcher_mod

    # pin the hoist: no function-local numpy import left in batcher.py
    tree = ast.parse(inspect.getsource(batcher_mod))
    for node in ast.walk(tree):
        if isinstance(node, ast.Import) and node.col_offset > 0:
            assert not any(
                a.name == "numpy" for a in node.names
            ), "numpy must be imported at module level in batcher.py"


def test_merged_path_conversion_error_fails_batch_not_flusher():
    """A group whose arrival prep raises (out-of-int64 value) fails
    that batch's callers with per-item errors — and the flusher stays
    alive to serve the next batch (parity with the flush-time path's
    failure envelope)."""

    async def scenario():
        be = TpuBackend(
            StoreConfig(rows=4, slots=SLOTS), buckets=BUCKETS
        )
        b = DeviceBatcher(
            be, batch_wait=0, batch_limit=1024, prep_at_arrival=True
        )
        b.start()
        bad = [
            RateLimitReq(
                name="x", unique_key="k", hits=2**200, limit=1,
                duration=1000,
            )
        ]
        with pytest.raises(Exception):
            await b.decide(bad, [False])
        # flusher survived: a good request still completes
        good = await b.decide(_mk_reqs("ok", 3), [False] * 3)
        assert [r.limit for r in good] == [1000, 1001, 1002]
        await b.stop()

    _run(scenario())
