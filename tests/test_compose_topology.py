"""deploy/docker-compose.yaml's topology, executed natively.

The compose file (r5) demos the reference's deployment shape: an etcd
service plus two nodes that discover each other through it (reference
docker-compose.yaml) via the vendored client. This image has no
docker, so the compose file itself can't boot here — instead this test
runs the SAME wiring with real processes: two daemons configured
exactly like the compose services (GUBER_ETCD_ENDPOINTS, no
GUBER_PEERS) against a protocol-real etcd (tests/_fake_etcd.py, real
gRPC + the vendored field-number-exact protos), and proves

- both nodes register and see each other (peerCount == 2 on both);
- the ring actually works: a request sent to the NON-owner node comes
  back with metadata.owner naming the other node (forwarded over
  gRPC), i.e. discovery produced a functioning cluster, not just a
  list.

When docker IS available, `docker compose up` in deploy/ runs the same
thing against real etcd; tests/test_etcd_vendored.py additionally runs
the client cycle against a live etcd when GUBER_TEST_ETCD is set.
"""

import json
import os
import pathlib
import subprocess
import sys
import time
import urllib.request

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent

GRPC = [29710, 29711]
HTTP = [29720, 29721]


def _daemon(i, etcd_port):
    env = dict(
        os.environ,
        PYTHONPATH=str(ROOT),
        GUBER_BACKEND="exact",
        JAX_PLATFORMS="cpu",
        GUBER_GRPC_ADDRESS=f"127.0.0.1:{GRPC[i]}",
        GUBER_HTTP_ADDRESS=f"127.0.0.1:{HTTP[i]}",
        GUBER_ADVERTISE_ADDRESS=f"127.0.0.1:{GRPC[i]}",
        GUBER_ETCD_ENDPOINTS=f"127.0.0.1:{etcd_port}",
    )
    env.pop("GUBER_PEERS", None)
    return subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=ROOT,
        env=env,
    )


def _get(url):
    return json.loads(urllib.request.urlopen(url, timeout=5).read())


def _post(port, body):
    return json.loads(
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/GetRateLimits",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            ),
            timeout=10,
        ).read()
    )


def test_compose_topology_discovers_and_forwards():
    from tests._fake_etcd import FakeEtcd

    etcd = FakeEtcd().start()
    daemons = [_daemon(i, etcd.port) for i in range(2)]
    try:
        # both nodes must discover each other through etcd
        deadline = time.monotonic() + 60
        counts = {}
        while time.monotonic() < deadline:
            for i in range(2):
                if daemons[i].poll() is not None:
                    pytest.fail(
                        f"daemon {i} died:\n{daemons[i].stdout.read()}"
                    )
                try:
                    counts[i] = _get(
                        f"http://127.0.0.1:{HTTP[i]}/v1/HealthCheck"
                    )["peerCount"]
                except OSError:
                    counts[i] = 0
            if counts.get(0) == 2 and counts.get(1) == 2:
                break
            time.sleep(0.3)
        assert counts == {0: 2, 1: 2}, counts

        # the discovered ring must FUNCTION: find a key owned by node 1
        # (response through node 0 carries metadata.owner), then verify
        # coherence by reading it back through the owner
        owner_key = None
        for i in range(64):
            out = _post(
                HTTP[0],
                {"requests": [{"name": "ct", "uniqueKey": f"k{i}",
                               "hits": 1, "limit": 9,
                               "duration": 60000}]},
            )
            resp = out["responses"][0]
            assert resp["error"] == "", resp
            if resp["metadata"].get("owner") == f"127.0.0.1:{GRPC[1]}":
                owner_key = f"k{i}"
                break
        assert owner_key is not None, "no key owned by node 1 in 64 tries"
        out = _post(
            HTTP[1],
            {"requests": [{"name": "ct", "uniqueKey": owner_key,
                           "hits": 0, "limit": 9, "duration": 60000}]},
        )
        # node 1 owns it: local decide, consumed hit visible
        resp = out["responses"][0]
        assert resp["remaining"] == "8" and "owner" not in resp["metadata"]
    finally:
        for d in daemons:
            d.terminate()
        for d in daemons:
            d.wait(timeout=10)
        etcd.stop()
