"""deploy/docker-compose.yaml's topology, executed natively.

The compose file (r5) demos the reference's deployment shape: an etcd
service plus two nodes that discover each other through it (reference
docker-compose.yaml) via the vendored client. This image has no
docker, so the compose file itself can't boot here — instead this test
runs the SAME wiring with real processes: two daemons configured
exactly like the compose services (GUBER_ETCD_ENDPOINTS, no
GUBER_PEERS) against a protocol-real etcd (tests/_fake_etcd.py, real
gRPC + the vendored field-number-exact protos), and proves

- both nodes register and see each other (peerCount == 2 on both);
- the ring actually works: a request sent to the NON-owner node comes
  back with metadata.owner naming the other node (forwarded over
  gRPC), i.e. discovery produced a functioning cluster, not just a
  list.

When docker IS available, `docker compose up` in deploy/ runs the same
thing against real etcd; tests/test_etcd_vendored.py additionally runs
the client cycle against a live etcd when GUBER_TEST_ETCD is set.

Isolation (r8 deflake): ports are allocated per-run (the r5-r7 version
pinned 2971x/2972x, which collided with leftovers/TIME_WAIT under
full-suite runs), and each daemon's output goes to its own temp FILE —
the old stdout=PIPE was never read until failure, so a chatty daemon
could fill the 64 KiB pipe buffer, block on a log write, and miss the
discovery deadline only when the rest of the suite made it slow.
"""

import json
import os
import pathlib
import subprocess
import sys
import time
import urllib.request

import pytest

from _util import free_ports

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _daemon(grpc_port, http_port, etcd_port, log_dir, i):
    env = dict(
        os.environ,
        PYTHONPATH=str(ROOT),
        GUBER_BACKEND="exact",
        JAX_PLATFORMS="cpu",
        GUBER_GRPC_ADDRESS=f"127.0.0.1:{grpc_port}",
        GUBER_HTTP_ADDRESS=f"127.0.0.1:{http_port}",
        GUBER_ADVERTISE_ADDRESS=f"127.0.0.1:{grpc_port}",
        GUBER_ETCD_ENDPOINTS=f"127.0.0.1:{etcd_port}",
    )
    env.pop("GUBER_PEERS", None)
    out = open(os.path.join(log_dir, f"daemon{i}.log"), "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cli.daemon"],
        stdout=out,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=ROOT,
        env=env,
    )
    proc._log = out  # noqa: SLF001 - test-local teardown handle
    return proc


def _read_log(proc) -> str:
    proc._log.flush()
    proc._log.seek(0)
    return proc._log.read()


def _get(url):
    return json.loads(urllib.request.urlopen(url, timeout=5).read())


def _post(port, body):
    return json.loads(
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/GetRateLimits",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            ),
            timeout=10,
        ).read()
    )


def test_compose_topology_discovers_and_forwards(tmp_path):
    from tests._fake_etcd import FakeEtcd

    grpc_ports = free_ports(2)
    http_ports = free_ports(2)
    log_dir = str(tmp_path)  # pytest-managed: cleaned up, kept on failure
    etcd = FakeEtcd().start()
    daemons = [
        _daemon(grpc_ports[i], http_ports[i], etcd.port, log_dir, i)
        for i in range(2)
    ]
    try:
        # both nodes must discover each other through etcd
        deadline = time.monotonic() + 60
        counts = {}
        while time.monotonic() < deadline:
            for i in range(2):
                if daemons[i].poll() is not None:
                    pytest.fail(
                        f"daemon {i} died:\n{_read_log(daemons[i])}"
                    )
                try:
                    counts[i] = _get(
                        f"http://127.0.0.1:{http_ports[i]}/v1/HealthCheck"
                    )["peerCount"]
                except OSError:
                    counts[i] = 0
            if counts.get(0) == 2 and counts.get(1) == 2:
                break
            time.sleep(0.3)
        assert counts == {0: 2, 1: 2}, counts

        # the discovered ring must FUNCTION: find a key owned by node 1
        # (response through node 0 carries metadata.owner), then verify
        # coherence by reading it back through the owner
        owner_key = None
        for i in range(64):
            out = _post(
                http_ports[0],
                {"requests": [{"name": "ct", "uniqueKey": f"k{i}",
                               "hits": 1, "limit": 9,
                               "duration": 60000}]},
            )
            resp = out["responses"][0]
            assert resp["error"] == "", resp
            owner = f"127.0.0.1:{grpc_ports[1]}"
            if resp["metadata"].get("owner") == owner:
                owner_key = f"k{i}"
                break
        assert owner_key is not None, "no key owned by node 1 in 64 tries"
        out = _post(
            http_ports[1],
            {"requests": [{"name": "ct", "uniqueKey": owner_key,
                           "hits": 0, "limit": 9, "duration": 60000}]},
        )
        # node 1 owns it: local decide, consumed hit visible
        resp = out["responses"][0]
        assert resp["remaining"] == "8" and "owner" not in resp["metadata"]
    finally:
        for d in daemons:
            d.terminate()
        for d in daemons:
            d.wait(timeout=10)
            d._log.close()
        etcd.stop()
