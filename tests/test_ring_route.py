"""Client-side per-owner fast routing (r18): sharding, GEBR healing,
downgrade accounting, decision identity, and the rolling-membership
soak.

The router moves the compiled edge's placement logic into
client_geb._RingRouter: crc32 ring points over the hello's membership,
fast-eligible items sharded per owner across per-node GEB connections,
every child pinned to the ROUTER's ring fingerprint so a moved ring
refuses with GEBR (never silently serves a mis-routed frame), and the
refusal heals by re-fetching the hello and retrying the refused shards
only.

- sharding + healing against fake listeners with deterministic
  10.99.* ring addresses: exact per-node item counts from an
  independent crc32 mirror, exactly one refresh per membership flip;
- mixed batches: string-only items (NO_BATCHING, chained) ride the
  primary connection concurrently with the fast shards, results land
  in caller order;
- auto-mode downgrade accounting (r18 satellite): no peer door and
  no ring_route each count + record their reason, silently serving
  over string frames;
- decision identity: a 3-node routed client against a 1-node string
  reference under the r10 fake-clock fuzz — byte-equal decisions;
- the r17 rolling-deploy soak through the ROUTING client: membership
  churn with rescale handoff, a sticky-over canary peeked after every
  flip — ZERO under-admissions, and the router heals (refreshes) on
  every change.
"""

import asyncio
import bisect
import zlib

import numpy as np
import pytest

from _util import free_ports
from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    ChainLevel,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.client_geb import AsyncGebClient
from gubernator_tpu.serve.edge_bridge import GebListener

T0 = 1_700_000_000_000

NODE_A = "10.99.0.1:81"
NODE_B = "10.99.0.2:81"
NODE_C = "10.99.0.3:81"


class FakeClock:
    def __init__(self):
        self.t = T0

    def __call__(self):
        return self.t


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


def _be():
    from gubernator_tpu.core.store import StoreConfig
    from gubernator_tpu.serve.backends import TpuBackend

    return TpuBackend(
        StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
    )


# -- independent placement mirror -------------------------------------------


def _owner_of(hosts, hash_key: str) -> str:
    """crc32 successor placement, written independently of
    client_geb._RingRouter so the test cross-checks the
    implementation instead of echoing it."""
    points = sorted(
        (zlib.crc32(h.encode("utf-8")) & 0xFFFFFFFF, h) for h in hosts
    )
    p = zlib.crc32(hash_key.encode("utf-8")) & 0xFFFFFFFF
    i = bisect.bisect_left([q for q, _ in points], p)
    if i == len(points):
        i = 0
    return points[i][1]


def _expected_counts(hosts, reqs):
    counts = {h: 0 for h in hosts}
    for r in reqs:
        counts[_owner_of(hosts, r.hash_key())] += 1
    return counts


# -- fake listener harness (the test_edge_ring_change pattern) ---------------


class FakeBackend:
    decide_submit_arrays = object()
    decide_submit = object()


class FakePicker:
    def __init__(self, hosts_self):
        self._peers = [
            type("P", (), {"host": h, "is_owner": mine})()
            for h, mine in hosts_self
        ]

    def peers(self):
        return self._peers


class CountingInstance:
    """Array fast path counting items and echoing limit-hits as
    remaining, plus a string echo path (for NO_BATCHING/chained
    items the router keeps on the primary connection)."""

    def __init__(self, self_host, hosts):
        self.backend = FakeBackend()
        self.picker = FakePicker([(h, h == self_host) for h in hosts])
        self.fast_items = 0
        inst = self

        class B:
            async def decide_arrays(self, fields, frame=True):
                n = fields["key_hash"].shape[0]
                inst.fast_items += n
                return (
                    np.zeros(n, np.int64),
                    fields["limit"],
                    fields["limit"] - fields["hits"],
                    np.zeros(n, np.int64),
                )

        class T:
            def observe_hashes(self, h):
                pass

        self.batcher = B()
        self.traffic = T()

    async def get_rate_limits(self, reqs, stage_frame=False):
        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=r.limit - r.hits, reset_time=1,
            )
            for r in reqs
        ]


def _rr_req(i, name="rr"):
    return RateLimitReq(
        name=name, unique_key=f"k{i}", hits=1, limit=10 + i,
        duration=60_000,
    )


def test_router_shards_per_owner_and_heals_on_membership_change():
    """40 fast-eligible items shard to EXACTLY the ring's owners (the
    independent crc32 mirror's counts, per node); a picker swap makes
    every in-flight fingerprint stale -> GEBR -> ONE hello re-fetch ->
    the same 40 items land on the 3-node split."""
    pa, pb, pc = free_ports(3)
    doors = {
        NODE_A: f"127.0.0.1:{pa}",
        NODE_B: f"127.0.0.1:{pb}",
        NODE_C: f"127.0.0.1:{pc}",
    }

    async def run():
        inst_a = CountingInstance(NODE_A, [NODE_A, NODE_B])
        inst_b = CountingInstance(NODE_B, [NODE_A, NODE_B])
        inst_c = CountingInstance(NODE_C, [NODE_A, NODE_B, NODE_C])
        listeners = [
            GebListener(inst, doors[node], peer_bridges=doors)
            for inst, node in (
                (inst_a, NODE_A), (inst_b, NODE_B), (inst_c, NODE_C)
            )
        ]
        for ln in listeners:
            await ln.start()
        client = AsyncGebClient(doors[NODE_A], ring_route=True)
        try:
            hello = await client.connect()
            assert len(hello.nodes) == 2
            st = client.stats()
            assert st["ring_routed"] is True
            assert st["downgrades"] == 0

            reqs = [_rr_req(i) for i in range(40)]
            want2 = _expected_counts([NODE_A, NODE_B], reqs)
            # the split must be non-trivial or the test proves nothing
            assert want2[NODE_A] > 0 and want2[NODE_B] > 0

            resps = await client.get_rate_limits(reqs)
            for i, r in enumerate(resps):
                assert r.status == Status.UNDER_LIMIT
                assert r.remaining == (10 + i) - 1, (i, r)
            assert inst_a.fast_items == want2[NODE_A]
            assert inst_b.fast_items == want2[NODE_B]
            assert inst_c.fast_items == 0
            assert client._router.refreshes == 0

            # membership change: C joins. The router's next frames
            # carry the 2-ring fingerprint -> every shard refused
            # (GEBR), ONE refresh, full re-route on the 3-ring.
            ring3 = [NODE_A, NODE_B, NODE_C]
            inst_a.picker = FakePicker(
                [(h, h == NODE_A) for h in ring3]
            )
            inst_b.picker = FakePicker(
                [(h, h == NODE_B) for h in ring3]
            )
            want3 = _expected_counts(ring3, reqs)
            assert want3[NODE_C] > 0

            resps = await client.get_rate_limits(reqs)
            for i, r in enumerate(resps):
                assert r.status == Status.UNDER_LIMIT
                assert r.remaining == (10 + i) - 1, (i, r)
            assert client._router.refreshes == 1
            assert inst_a.fast_items == want2[NODE_A] + want3[NODE_A]
            assert inst_b.fast_items == want2[NODE_B] + want3[NODE_B]
            assert inst_c.fast_items == want3[NODE_C]
            assert client.stats()["downgrades"] == 0
        finally:
            await client.close()
            for ln in listeners:
                await ln.stop()

    asyncio.run(run())


def test_router_mixed_batch_lands_in_caller_order():
    """A batch mixing fast-eligible, NO_BATCHING, and chained items:
    the ineligible ones ride the primary's string frames concurrently
    with the fast shards; every response lands at its request's
    index."""
    pa, pb = free_ports(2)
    doors = {NODE_A: f"127.0.0.1:{pa}", NODE_B: f"127.0.0.1:{pb}"}

    async def run():
        inst_a = CountingInstance(NODE_A, [NODE_A, NODE_B])
        inst_b = CountingInstance(NODE_B, [NODE_A, NODE_B])
        listeners = [
            GebListener(inst_a, doors[NODE_A], peer_bridges=doors),
            GebListener(inst_b, doors[NODE_B], peer_bridges=doors),
        ]
        for ln in listeners:
            await ln.start()
        client = AsyncGebClient(doors[NODE_A], ring_route=True)
        try:
            await client.connect()
            reqs = []
            for i in range(12):
                kw = {}
                if i % 3 == 0:
                    kw["behavior"] = Behavior.NO_BATCHING
                elif i % 3 == 2:
                    kw["chain"] = [ChainLevel("cg:mix", 1 << 30, 0)]
                reqs.append(
                    RateLimitReq(
                        name="mx", unique_key=f"m{i}", hits=1,
                        limit=20 + i, duration=60_000, **kw,
                    )
                )
            resps = await client.get_rate_limits(reqs)
            assert len(resps) == 12
            for i, r in enumerate(resps):
                assert r.status == Status.UNDER_LIMIT
                assert r.remaining == (20 + i) - 1, (i, r)
            # only the i%3==1 third was fast-eligible; the rest went
            # down the string/instance path (counted nowhere)
            fast = [r for i, r in enumerate(reqs) if i % 3 == 1]
            want = _expected_counts([NODE_A, NODE_B], fast)
            assert inst_a.fast_items == want[NODE_A]
            assert inst_b.fast_items == want[NODE_B]
        finally:
            await client.close()
            for ln in listeners:
                await ln.stop()

    asyncio.run(run())


def test_downgrade_reason_peer_door_unknown():
    """ring_route=True on a multi-node ring whose hello can't name a
    peer's frame door (no GUBER_GEB_PEER_DOORS, host without the
    symmetric port shape): the downgrade is COUNTED with its reason
    and the client silently keeps serving over string frames."""
    (pa,) = free_ports(1)

    async def run():
        # "nodeB" has no port: the symmetric-port door derivation
        # yields nothing and no peer_bridges override exists
        inst = CountingInstance(NODE_A, [NODE_A, "nodeB"])
        listener = GebListener(inst, f"127.0.0.1:{pa}")
        await listener.start()
        client = AsyncGebClient(f"127.0.0.1:{pa}", ring_route=True)
        try:
            await client.connect()
            st = client.stats()
            assert st["ring_routed"] is False
            assert st["use_fast"] is False
            assert st["downgrades"] == 1
            assert st["downgrade_reason"].startswith(
                "peer door unknown"
            )
            resps = await client.get_rate_limits(
                [_rr_req(0, name="dg")]
            )
            assert resps[0].status == Status.UNDER_LIMIT
            assert inst.fast_items == 0  # string path served it
        finally:
            await client.close()
            await listener.stop()

    asyncio.run(run())


def test_downgrade_reason_multi_node_without_ring_route():
    """The pre-r18 shape: auto mode on a multi-node ring WITHOUT
    ring_route downgrades to string frames — now counted + reasoned
    instead of silent."""
    pa, pb = free_ports(2)
    doors = {NODE_A: f"127.0.0.1:{pa}", NODE_B: f"127.0.0.1:{pb}"}

    async def run():
        inst = CountingInstance(NODE_A, [NODE_A, NODE_B])
        listener = GebListener(inst, doors[NODE_A], peer_bridges=doors)
        await listener.start()
        client = AsyncGebClient(doors[NODE_A])  # ring_route off
        try:
            await client.connect()
            st = client.stats()
            assert st["ring_routed"] is False
            assert st["use_fast"] is False
            assert st["downgrades"] == 1
            assert st["downgrade_reason"].startswith("multi-node ring")
            resps = await client.get_rate_limits(
                [_rr_req(1, name="dg2")]
            )
            assert resps[0].status == Status.UNDER_LIMIT
            assert inst.fast_items == 0
        finally:
            await client.close()
            await listener.stop()

    asyncio.run(run())


# -- decision identity ------------------------------------------------------


def _fuzz_stream(rng, keys, steps):
    for step in range(steps):
        n = int(rng.integers(1, 7))
        batch = []
        for _ in range(n):
            k = int(rng.integers(len(keys)))
            batch.append(
                RateLimitReq(
                    name="ringdoor",
                    unique_key=keys[k],
                    hits=int(rng.choice([0, 1, 1, 1, 2, 9])),
                    limit=int(rng.choice([1, 2, 3, 50])),
                    duration=int(rng.choice([400, 2000, 60_000])),
                    algorithm=Algorithm(k % 2),
                )
            )
        yield step, batch, int(rng.choice([0, 0, 1, 7, 150, 500, 2500]))


def test_ring_routed_vs_single_node_string_identity_fuzz(monkeypatch):
    """A ring-routed client over a REAL 3-node cluster decides
    byte-identically to a 1-node string reference under the r10
    fake-clock fuzz: every key lands on exactly one store in both
    topologies, so (status, limit, remaining, reset_time, error) match
    item for item."""
    from gubernator_tpu.cluster import LocalCluster

    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    ports = free_ports(8)
    routed_cluster = LocalCluster(
        [f"127.0.0.1:{p}" for p in ports[:3]],
        backend_factory=_be,
        geb_ports=list(ports[3:6]),
    )
    ref_cluster = LocalCluster(
        [f"127.0.0.1:{ports[6]}"],
        backend_factory=_be,
        geb_ports=[ports[7]],
    )
    for c in (routed_cluster, ref_cluster):
        c.start()
        for s in c.servers:
            if s.instance.shed is not None:
                s.instance.shed.now_fn = clock
    try:

        async def run():
            routed = AsyncGebClient(
                f"127.0.0.1:{ports[3]}", ring_route=True
            )
            ref = AsyncGebClient(
                f"127.0.0.1:{ports[7]}", mode="string", shm="off"
            )
            rng = np.random.default_rng(53)
            keys = [f"rk{i}" for i in range(12)]
            try:
                hello = await routed.connect()
                assert len(hello.nodes) == 3
                st = routed.stats()
                assert st["ring_routed"] is True
                assert st["downgrades"] == 0
                for step, batch, dt in _fuzz_stream(rng, keys, 70):
                    clock.t += dt
                    a = await ref.get_rate_limits(batch)
                    b = await routed.get_rate_limits(batch)
                    for i, (x, y) in enumerate(zip(a, b)):
                        tx = (int(x.status), x.limit, x.remaining,
                              x.reset_time, x.error)
                        ty = (int(y.status), y.limit, y.remaining,
                              y.reset_time, y.error)
                        assert tx == ty, (step, i, batch[i], tx, ty)
            finally:
                await ref.close()
                await routed.close()

        asyncio.run(run())
    finally:
        routed_cluster.stop()
        ref_cluster.stop()


# -- rolling-membership soak (r17's deploy replay, routed client) -----------


def test_rolling_membership_soak_zero_canary_under_admissions():
    """The r18 acceptance soak: a 3-node ring with elastic rescale,
    membership churned leave/rejoin through the CANARY OWNER twice,
    all traffic through the ring-routing client. The sticky-over
    canary (created-over window, r17 semantics) is peeked after every
    flip: ZERO under-admissions, ever. Fast background batches after
    each flip prove the router heals (GEBR -> refresh -> served) on
    every single change."""
    from gubernator_tpu.serve.config import BehaviorConfig, ServerConfig
    from gubernator_tpu.serve.server import Server

    ports = free_ports(6)
    addrs = [f"127.0.0.1:{p}" for p in ports[:3]]
    gebs = ports[3:]
    doors = ",".join(
        f"{a}=127.0.0.1:{g}" for a, g in zip(addrs, gebs)
    )

    async def run():
        servers = []
        for a, g in zip(addrs, gebs):
            conf = ServerConfig(
                grpc_address=a,
                http_address="",  # default is localhost:80
                advertise_address=a,
                backend="exact",
                behaviors=BehaviorConfig(global_sync_wait=0.05),
                rescale=True,
                replication_sync_wait=60.0,  # background flusher quiet
                geb_port=g,
                geb_peer_doors=doors,
            )
            conf.peers = list(addrs)
            s = Server(conf, backend=_be())
            await s.start()
            servers.append(s)

        async def set_ring(members):
            """One membership flip, everywhere: new pickers, then the
            rescale handoff (movers ship their owned windows), then
            the double-serve windows closed so the NEW owner serves —
            the deterministic deploy step (test_rescale's pattern)."""
            for s in servers:
                me = s.conf.grpc_address
                await s.instance.set_peers([
                    PeerInfo(address=h, is_owner=(h == me))
                    for h in members
                ])
            for s in servers:
                await s.instance.rescale.flush_once()
            for s in servers:
                s.instance.rescale._transition = None

        # deterministic handoffs: the server's background flusher
        # would pop a queued ring change before the test's manual
        # flush_once (which then sees an empty queue and returns while
        # the real handoff RPC is still in flight) — stop it and drive
        # every flush by hand (stop() is idempotent; Server.stop
        # re-calls it). Startup transitions (initial set_peers) must
        # not leak into the soak's windows either.
        for s in servers:
            await s.instance.rescale.stop()
            s.instance.rescale._transition = None

        client = AsyncGebClient(
            f"127.0.0.1:{gebs[0]}", ring_route=True, timeout=30.0
        )
        under_admissions = 0
        try:
            hello = await client.connect()
            assert len(hello.nodes) == 3
            assert client.stats()["ring_routed"] is True

            # a canary whose owner is NOT the client's primary, so the
            # owner itself can leave the ring (the interesting case)
            ck = next(
                f"c{i}" for i in range(512)
                if _owner_of(addrs, f"soak_c{i}") != addrs[0]
            )

            def canary(hits):
                return RateLimitReq(
                    name="soak", unique_key=ck, hits=hits, limit=1,
                    duration=600_000, behavior=Behavior.NO_BATCHING,
                )

            def bg(tag):
                return [
                    RateLimitReq(
                        name="soakbg", unique_key=f"{tag}{i}", hits=1,
                        limit=1 << 30, duration=600_000,
                    )
                    for i in range(8)
                ]

            # hits > limit on a fresh key: a created-over window —
            # sticky OVER_LIMIT for the whole duration (r17 semantics)
            r = (await client.get_rate_limits([canary(2)]))[0]
            assert r.error == "" and r.status == Status.OVER_LIMIT

            owner = _owner_of(addrs, f"soak_{ck}")
            other = next(
                a for a in addrs[1:] if a != owner
            )
            flips = 0
            for leaver in (owner, other, owner):
                for members in (
                    [a for a in addrs if a != leaver],  # leave
                    list(addrs),                        # rejoin
                ):
                    await set_ring(members)
                    flips += 1
                    outs = await client.get_rate_limits(
                        bg(f"f{flips}_")
                    )
                    assert all(
                        o.status == Status.UNDER_LIMIT and not o.error
                        for o in outs
                    )
                    r = (await client.get_rate_limits([canary(0)]))[0]
                    assert r.error == ""
                    if r.status != Status.OVER_LIMIT:
                        under_admissions += 1
            assert under_admissions == 0, (
                f"quota amnesia: {under_admissions} canary peeks "
                f"under-admitted across {flips} membership flips"
            )
            # the router healed on every flip: each post-flip batch
            # hit a stale fingerprint (GEBR) and re-fetched the ring
            assert client._router.refreshes >= flips, (
                client._router.refreshes, flips
            )
        finally:
            await client.close()
            for s in servers:
                await s.stop()

    asyncio.run(run())
