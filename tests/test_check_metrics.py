"""Tier-1 drift gate: every metric family declared in serve/metrics.py
must be documented in docs/operations.md (r16 satellite; the same
no-drift contract check_knobs.py applies to GUBER_* env knobs). Run
`python scripts/check_metrics.py` for the per-metric diff."""

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _mod():
    sys.path.insert(0, str(ROOT / "scripts"))
    try:
        import check_metrics
    finally:
        sys.path.pop(0)
    return check_metrics


def test_scanner_finds_real_declarations():
    names = _mod().declared_metrics()
    # spot-check one family per declaration shape/era: a reference
    # Counter, a histogram, a labelled gauge, and r16 additions
    for n in (
        "grpc_request_counts",
        "device_batch_size",
        "peer_breaker_state",
        "batcher_queue_depth",
        "traces_recorded_total",
    ):
        assert n in names, (n, sorted(names))
    # names are unique (a duplicate declaration would crash prometheus
    # at import, but the scanner must not mask one either)
    assert len(names) == len(set(names))


def test_scanner_detects_ctor_shapes(tmp_path):
    """Direct and attribute-qualified constructor calls must both
    count; non-literal first args must not crash the scan."""
    p = tmp_path / "m.py"
    p.write_text(
        "from prometheus_client import Counter, Gauge\n"
        "import prometheus_client as pc\n"
        'A = Counter("direct_ctor_total", "d")\n'
        'B = pc.Gauge("attr_ctor", "d")\n'
        "name = 'dynamic'\n"
        "C = Counter(name, 'd')\n"  # non-literal: skipped, no crash
    )
    names = _mod().declared_metrics(p)
    assert names == ["direct_ctor_total", "attr_ctor"]


def test_every_declared_metric_is_documented():
    assert _mod().main() == 0, (
        "metric declared in serve/metrics.py missing from "
        "docs/operations.md — run scripts/check_metrics.py"
    )
