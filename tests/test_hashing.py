"""Hashing tests: ring parity with the reference and native/fallback paths."""

import zlib

import numpy as np
import pytest

from gubernator_tpu.core import hashing


def test_ring_hash_is_crc32_ieee():
    # the reference picker defaults to crc32.ChecksumIEEE (hash.go:40-42);
    # placement compatibility requires the identical function
    for s in ["127.0.0.1:81", "test_account:1234", ""]:
        assert hashing.ring_hash(s) == zlib.crc32(s.encode())


def test_slot_hash_batch_consistent_with_single():
    keys = [f"k:{i}" for i in range(100)]
    batch = hashing.slot_hash_batch(keys)
    assert batch.dtype == np.uint64
    for i in (0, 17, 99):
        assert hashing.slot_hash(keys[i]) == int(batch[i])


def test_slot_hash_no_trivial_collisions():
    keys = [f"name_{i}_account:{i}" for i in range(50_000)]
    h = hashing.slot_hash_batch(keys)
    assert len(set(h.tolist())) == len(keys)


def test_native_matches_known_xxh64_vectors():
    hashlib_native = pytest.importorskip(
        "gubernator_tpu.native.hashlib_native",
        reason="native hash library not built (make -C gubernator_tpu/native)",
    )
    # published XXH64 seed-0 vectors
    v = hashlib_native.hash_batch_seed(["", "a", "abc"], 0)
    assert [int(x) for x in v] == [
        0xEF46DB3751D8E999,
        0xD24EC4F1A98C6E5B,
        0x44BC2CF5AD770999,
    ]
    # a >=32-byte input exercises the 4-lane stripe + merge rounds
    # (digest cross-checked against an independent implementation that
    # reproduces the published seed-0 vectors)
    long_key = "0123456789abcdef0123456789abcdef0123456789"
    got = int(hashlib_native.hash_batch_seed([long_key], 7)[0])
    assert got == 0x9CDB6129259B938E
    # crc batch parity with zlib
    keys = ["a", "abc", "gubernator_tpu", ""]
    crc = hashlib_native.crc32_batch(keys)
    for i, k in enumerate(keys):
        assert int(crc[i]) == zlib.crc32(k.encode())


def test_mix64_avalanche():
    x = np.arange(1, 10_000, dtype=np.uint64)
    mixed = hashing.mix64(x)
    # sequential inputs must not produce sequential outputs
    assert len(set((mixed % np.uint64(1024)).tolist())) > 600


def test_native_presort_matches_numpy():
    """The C presort must order exactly like the numpy reference (stable
    argsort of group_sort_key_np) — decide_presorted's caller contract
    depends on it. The bucket sizes cover BOTH native paths: the
    counting-sort fast path (<= 2^16 buckets) and the radix fallback."""
    hashlib_native = pytest.importorskip(
        "gubernator_tpu.native.hashlib_native"
    )
    from gubernator_tpu.core.store import group_sort_key_np

    rng = np.random.default_rng(3)
    for n in (0, 1, 7, 1000, 16384):
        for buckets in (1 << 10, 1 << 15, 1 << 16, 1 << 21):
            kh = rng.integers(0, 2**63, n, dtype=np.int64).astype(np.uint64)
            # force duplicates (stability matters)
            if n > 10:
                kh[n // 2 :] = kh[: n - n // 2]
            want = np.argsort(
                group_sort_key_np(kh, buckets), kind="stable"
            )
            got = hashlib_native.presort(kh, buckets)
            assert (want == got).all(), (n, buckets)


def test_native_presort_grouped_matches_numpy():
    """Grouped + sharded native presorts must match their numpy twins
    bit for bit (order, group ids, leader positions, shard/group counts)
    across both the counting and radix paths, including non-power-of-two
    shard counts."""
    hashlib_native = pytest.importorskip(
        "gubernator_tpu.native.hashlib_native"
    )
    from gubernator_tpu.core.engine import _np_presort_grouped
    from gubernator_tpu.parallel.sharded import (
        _np_presort_sharded,
        _np_presort_sharded_grouped,
    )

    rng = np.random.default_rng(5)
    for n in (0, 1, 9, 1000, 8192):
        for buckets in (1 << 10, 1 << 16, 1 << 21):
            kh = rng.integers(0, 2**63, n, dtype=np.int64).astype(np.uint64)
            if n > 10:
                kh[n // 2 :] = kh[: n - n // 2]
            o1, g1, l1, G1 = _np_presort_grouped(kh, buckets)
            o2, g2, l2, G2 = hashlib_native.presort_grouped(kh, buckets)
            assert G1 == G2, (n, buckets)
            assert (np.asarray(o2) == o1).all(), (n, buckets)
            assert (np.asarray(g2)[:n] == g1).all(), (n, buckets)
            assert (np.asarray(l2)[:G1] == l1).all(), (n, buckets)
            for shards in (1, 2, 8, 13):
                so1, c1 = _np_presort_sharded(kh, buckets, shards)
                so2, c2 = hashlib_native.presort_sharded(kh, buckets, shards)
                assert (np.asarray(so2) == so1).all(), (n, buckets, shards)
                assert (np.asarray(c2) == c1).all(), (n, buckets, shards)
                r1 = _np_presort_sharded_grouped(kh, buckets, shards)
                r2 = hashlib_native.presort_sharded_grouped(
                    kh, buckets, shards
                )
                G = r1[3].shape[0]
                assert (np.asarray(r2[0]) == r1[0]).all(), (n, buckets, shards)
                assert (np.asarray(r2[1]) == r1[1]).all(), (n, buckets, shards)
                assert (np.asarray(r2[2])[:n] == r1[2]).all(), (
                    n, buckets, shards,
                )
                assert (np.asarray(r2[3])[:G] == r1[3]).all(), (
                    n, buckets, shards,
                )
                assert (np.asarray(r2[4]) == r1[4]).all(), (n, buckets, shards)
