"""Hashing tests: ring parity with the reference and native/fallback paths."""

import zlib

import numpy as np
import pytest

from gubernator_tpu.core import hashing


def test_ring_hash_is_crc32_ieee():
    # the reference picker defaults to crc32.ChecksumIEEE (hash.go:40-42);
    # placement compatibility requires the identical function
    for s in ["127.0.0.1:81", "test_account:1234", ""]:
        assert hashing.ring_hash(s) == zlib.crc32(s.encode())


def test_slot_hash_batch_consistent_with_single():
    keys = [f"k:{i}" for i in range(100)]
    batch = hashing.slot_hash_batch(keys)
    assert batch.dtype == np.uint64
    for i in (0, 17, 99):
        assert hashing.slot_hash(keys[i]) == int(batch[i])


def test_slot_hash_no_trivial_collisions():
    keys = [f"name_{i}_account:{i}" for i in range(50_000)]
    h = hashing.slot_hash_batch(keys)
    assert len(set(h.tolist())) == len(keys)


def test_native_matches_known_xxh64_vectors():
    hashlib_native = pytest.importorskip(
        "gubernator_tpu.native.hashlib_native",
        reason="native hash library not built (make -C gubernator_tpu/native)",
    )
    # published XXH64 seed-0 vectors
    v = hashlib_native.hash_batch_seed(["", "a", "abc"], 0)
    assert [int(x) for x in v] == [
        0xEF46DB3751D8E999,
        0xD24EC4F1A98C6E5B,
        0x44BC2CF5AD770999,
    ]
    # a >=32-byte input exercises the 4-lane stripe + merge rounds
    # (digest cross-checked against an independent implementation that
    # reproduces the published seed-0 vectors)
    long_key = "0123456789abcdef0123456789abcdef0123456789"
    got = int(hashlib_native.hash_batch_seed([long_key], 7)[0])
    assert got == 0x9CDB6129259B938E
    # crc batch parity with zlib
    keys = ["a", "abc", "gubernator_tpu", ""]
    crc = hashlib_native.crc32_batch(keys)
    for i, k in enumerate(keys):
        assert int(crc[i]) == zlib.crc32(k.encode())


def test_mix64_avalanche():
    x = np.arange(1, 10_000, dtype=np.uint64)
    mixed = hashing.mix64(x)
    # sequential inputs must not produce sequential outputs
    assert len(set((mixed % np.uint64(1024)).tolist())) > 600


def test_native_presort_matches_numpy():
    """The C radix presort must order exactly like the numpy reference
    (stable argsort of group_sort_key_np) — decide_presorted's caller
    contract depends on it."""
    hashlib_native = pytest.importorskip(
        "gubernator_tpu.native.hashlib_native"
    )
    from gubernator_tpu.core.store import group_sort_key_np

    rng = np.random.default_rng(3)
    for n in (0, 1, 7, 1000, 16384):
        for buckets in (1 << 10, 1 << 15, 1 << 21):
            kh = rng.integers(0, 2**63, n, dtype=np.int64).astype(np.uint64)
            # force duplicates (stability matters)
            if n > 10:
                kh[n // 2 :] = kh[: n - n // 2]
            want = np.argsort(
                group_sort_key_np(kh, buckets), kind="stable"
            )
            got = hashlib_native.presort(kh, buckets)
            assert (want == got).all(), (n, buckets)
