"""Coverage for the last untested L7 pieces: the CLI load generator
(reference cmd/gubernator-cli) driven against a real cluster, and the
client helper functions (reference client.go:52-82 + the Python
client's sleep_until_reset)."""

import contextlib
import io
import time

import pytest

from gubernator_tpu.api.types import RateLimitResp, millisecond_now
from gubernator_tpu.client import random_peer, random_string, sleep_until_reset
from gubernator_tpu.cluster import LocalCluster
from gubernator_tpu.serve.backends import ExactBackend


def test_random_helpers():
    peers = ["a:1", "b:2", "c:3"]
    seen = {random_peer(peers) for _ in range(100)}
    assert seen <= set(peers) and len(seen) > 1
    s1, s2 = random_string("id-"), random_string("id-")
    assert s1.startswith("id-") and s2.startswith("id-") and s1 != s2
    assert len(random_string("", 10)) == 10


def test_sleep_until_reset_waits_until_window():
    # reset 150ms out: the helper must block ~that long (reference
    # python client's convenience sleep)
    resp = RateLimitResp(reset_time=millisecond_now() + 150)
    t0 = time.monotonic()
    sleep_until_reset(resp)
    waited = time.monotonic() - t0
    assert waited >= 0.10, waited
    # a reset in the past returns immediately
    t0 = time.monotonic()
    sleep_until_reset(RateLimitResp(reset_time=millisecond_now() - 1000))
    assert time.monotonic() - t0 < 0.05


def test_loadgen_against_cluster(capsys):
    """The load generator's replay loop end to end: bounded duration run
    against a 2-node cluster; every request answered, OVER_LIMIT
    responses dumped, summary line printed."""
    import asyncio

    from gubernator_tpu.cli import loadgen
    from _util import free_ports

    cluster = LocalCluster(
        [f"127.0.0.1:{p}" for p in free_ports(2)],
        backend_factory=lambda: ExactBackend(10_000),
    )
    cluster.start()
    try:
        stderr = io.StringIO()
        with contextlib.redirect_stderr(stderr):
            asyncio.run(
                loadgen.run(
                    cluster.peer_at(0), keys=40, concurrency=3,
                    batch=8, duration=2.0,
                )
            )
        summary = stderr.getvalue()
        assert "sent=" in summary and "errors=0" in summary, summary
        # small limits (1..100) replayed for 2s: some keys must trip
        out = capsys.readouterr().out
        assert "over the limit" in out
    finally:
        cluster.stop()
