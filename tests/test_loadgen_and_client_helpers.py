"""Coverage for the last untested L7 pieces: the CLI load generator
(reference cmd/gubernator-cli) driven against a real cluster, and the
client helper functions (reference client.go:52-82 + the Python
client's sleep_until_reset)."""

import contextlib
import io
import time

import pytest

from gubernator_tpu.api.types import RateLimitResp, millisecond_now
from gubernator_tpu.client import random_peer, random_string, sleep_until_reset
from gubernator_tpu.cluster import LocalCluster
from gubernator_tpu.serve.backends import ExactBackend


def test_random_helpers():
    peers = ["a:1", "b:2", "c:3"]
    seen = {random_peer(peers) for _ in range(100)}
    assert seen <= set(peers) and len(seen) > 1
    s1, s2 = random_string("id-"), random_string("id-")
    assert s1.startswith("id-") and s2.startswith("id-") and s1 != s2
    assert len(random_string("", 10)) == 10


def test_sleep_until_reset_waits_until_window():
    # reset 150ms out: the helper must block ~that long (reference
    # python client's convenience sleep)
    resp = RateLimitResp(reset_time=millisecond_now() + 150)
    t0 = time.monotonic()
    sleep_until_reset(resp)
    waited = time.monotonic() - t0
    assert waited >= 0.10, waited
    # a reset in the past returns immediately
    t0 = time.monotonic()
    sleep_until_reset(RateLimitResp(reset_time=millisecond_now() - 1000))
    assert time.monotonic() - t0 < 0.05


def test_endpoint_parse_helper():
    """The shared endpoint parser (r12): TCP vs unix shapes, and the
    loud IPv6 refusal every client/bridge site now goes through
    instead of a silent last-colon misparse."""
    from gubernator_tpu.endpoints import (
        endpoint_is_ipv6ish,
        parse_endpoint,
        reject_ipv6_endpoint,
    )

    assert parse_endpoint("10.0.0.1:81") == ("tcp", ("10.0.0.1", 81))
    assert parse_endpoint("svc.local:9090") == (
        "tcp", ("svc.local", 9090),
    )
    assert parse_endpoint("/tmp/x.sock") == ("unix", "/tmp/x.sock")
    assert parse_endpoint("unix:/tmp/x.sock") == ("unix", "/tmp/x.sock")

    for bad in ("[::1]:81", "::1", "fe80::1:81", "2001:db8::7:9090"):
        assert endpoint_is_ipv6ish(bad), bad
        with pytest.raises(ValueError, match="IPv6"):
            parse_endpoint(bad, "test endpoint")
        with pytest.raises(ValueError, match="IPv6"):
            reject_ipv6_endpoint(bad, "test endpoint")
    for bad in ("", "hostonly", ":81", "host:", "host:abc", "host:0"):
        with pytest.raises(ValueError):
            parse_endpoint(bad, "test endpoint")


def test_clients_refuse_ipv6_endpoints_loudly():
    """Both packaged clients route through the shared parser: an IPv6
    endpoint raises at construction with a message naming the rule,
    never a downstream resolver/unix-path misparse."""
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.client_geb import AsyncGebClient, GebClient

    for ctor in (V1Client, AsyncGebClient, GebClient):
        with pytest.raises(ValueError, match="IPv6"):
            ctor("[::1]:81")
    # the gRPC client also refuses unix paths with guidance (they are
    # the GEB client's transport)
    with pytest.raises(ValueError, match="unix"):
        V1Client("/tmp/some.sock")


def test_loadgen_against_cluster(capsys):
    """The load generator's replay loop end to end: bounded duration run
    against a 2-node cluster; every request answered, OVER_LIMIT
    responses dumped, summary line printed."""
    import asyncio

    from gubernator_tpu.cli import loadgen
    from _util import free_ports

    cluster = LocalCluster(
        [f"127.0.0.1:{p}" for p in free_ports(2)],
        backend_factory=lambda: ExactBackend(10_000),
    )
    cluster.start()
    try:
        stderr = io.StringIO()
        with contextlib.redirect_stderr(stderr):
            asyncio.run(
                loadgen.run(
                    cluster.peer_at(0), keys=40, concurrency=3,
                    batch=8, duration=2.0,
                )
            )
        summary = stderr.getvalue()
        assert "sent=" in summary and "errors=0" in summary, summary
        # small limits (1..100) replayed for 2s: some keys must trip
        out = capsys.readouterr().out
        assert "over the limit" in out
    finally:
        cluster.stop()


def test_loadgen_geb_protocol_and_shed_shape():
    """`--protocol geb` end to end against a daemon GEB door with the
    shed-r10 workload shape: the generator must speak the binary
    client protocol (no gRPC involved), hit roughly the requested
    over-limit share, and report a machine-readable summary."""
    import asyncio

    from _util import free_ports
    from gubernator_tpu.cli import loadgen

    g, geb = free_ports(2)
    cluster = LocalCluster(
        [f"127.0.0.1:{g}"],
        backend_factory=lambda: ExactBackend(10_000),
        geb_ports=[geb],
    )
    cluster.start()
    try:
        summary = asyncio.run(
            loadgen.run(
                f"127.0.0.1:{geb}", keys=0, concurrency=4, batch=50,
                duration=1.0, protocol="geb", share=0.5, quiet=True,
            )
        )
        assert summary["protocol"] == "geb"
        assert summary["errors"] == 0
        assert summary["sent"] > 0
        # hot keys freeze over limit after their first touch, so the
        # measured share converges on the target from below
        assert 0.3 <= summary["over_limit_share"] <= 0.55, summary
    finally:
        cluster.stop()
