"""/metrics endpoint contract (r16 satellite): scrape a LIVE daemon,
parse the Prometheus exposition text, and assert the documented metric
families are present with SANE label cardinality — the `peer` and
`stage` label sets must stay bounded by cluster membership and the
fixed stage list, never grow per-key or per-request.

The family list is derived from serve/metrics.py via the same AST
scanner the doc drift gate uses (scripts/check_metrics.py), so a newly
declared metric is automatically held to this contract too.
"""

import pathlib
import sys
import time
import urllib.request

from prometheus_client.parser import text_string_to_metric_families

from _util import free_ports
from gubernator_tpu.api.types import RateLimitReq
from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import LocalCluster

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _declared():
    sys.path.insert(0, str(ROOT / "scripts"))
    try:
        import check_metrics
    finally:
        sys.path.pop(0)
    return check_metrics.declared_metrics()


def _scrape(http_port) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{http_port}/metrics", timeout=10
    ) as r:
        text = r.read().decode()
    fams = {}
    for fam in text_string_to_metric_families(text):
        fams[fam.name] = fam
    return fams


def test_metrics_endpoint_families_and_label_cardinality():
    g1, g2, http = free_ports(3)
    addrs = [f"127.0.0.1:{g1}", f"127.0.0.1:{g2}"]
    cluster = LocalCluster(
        addrs,
        http_addresses=[f"127.0.0.1:{http}", ""],
        trace_sample=1.0,  # exercise the trace counters too
    )
    cluster.start()
    try:
        # drive real traffic: owned + forwarded keys through the gRPC
        # door so per-peer series and the device/stage paths populate
        with V1Client(addrs[0]) as client:
            for i in range(30):
                resps = client.get_rate_limits(
                    [
                        RateLimitReq(
                            name="m", unique_key=f"mk{i}", hits=1,
                            limit=100, duration=60_000,
                        )
                    ],
                    timeout=10,
                )
                assert not resps[0].error
        time.sleep(0.1)
        fams = _scrape(http)

        # prometheus_client strips the _total suffix into family
        # names; accept either spelling like the doc gate does
        present = set(fams)
        for name in _declared():
            base = name[:-6] if name.endswith("_total") else name
            # label-carrying families only exist once a label value
            # was observed; the always-set and traffic-driven ones
            # must be there
            if name in (
                "grpc_request_counts",
                "grpc_request_duration_milliseconds",
                "cache_access_count",
                "device_batch_size",
                "device_launch_milliseconds",
                "distinct_keys_estimate",
                "serving_stage_seconds_total",
                "serving_stage_samples_total",
                "batcher_queue_depth",
                "batcher_queue_oldest_age_seconds",
                "prep_pool_backlog",
                "shed_hits_total",
                "shed_lookups_total",
                "shed_entries",
                "traces_started_total",
                "traces_recorded_total",
                "traces_tail_captured_total",
                "traces_dropped_total",
                "trace_slow_threshold_ms",
                "cache_size",
                "drain_duration_seconds",
                "peer_breaker_state",
            ):
                assert base in present or name in present, (
                    name, sorted(present),
                )

        # traffic really flowed through the metered doors
        grpc_counts = {
            tuple(sorted(s.labels.items())): s.value
            for s in fams["grpc_request_counts"].samples
        }
        assert sum(grpc_counts.values()) >= 30

        # bounded `peer` label set: THIS cluster's members are present,
        # and every series is labelled by a peer ADDRESS (host:port) —
        # never a per-key or per-request value. (The registry is
        # process-global, so a full-suite run legitimately carries
        # other tests' cluster addresses too.)
        import re

        for fam_name in ("peer_breaker_state",):
            if fam_name in fams:
                peers = {
                    s.labels["peer"] for s in fams[fam_name].samples
                }
                assert set(addrs) <= peers, (peers, addrs)
                assert all(
                    re.fullmatch(r"[\w.\-]+:\d{1,5}", p) for p in peers
                ), peers

        # bounded `stage` label set: exactly the stage clock's names
        from gubernator_tpu.serve.stages import (
            PER_BATCH,
            PER_CALL,
            PER_FRAME,
        )

        known = set(PER_FRAME) | set(PER_BATCH) | set(PER_CALL)
        stages = {
            s.labels["stage"]
            for s in fams["serving_stage_seconds_total"].samples
        }
        assert stages <= known, stages
        assert "instance_route" in stages  # traffic populated it

        # trace counters moved (trace_sample=1.0 on every node)
        started = next(
            s.value for s in fams["traces_started_total"].samples
        )
        assert started >= 30
    finally:
        cluster.stop()
