"""Malformed-input fuzz for the native edge (VERDICT r1 weak #9).

Drives the real guber-edge binary (hand-rolled HTTP/1.1 + JSON parsing)
with a corpus of hostile inputs — truncated bodies, numbers cut by
Content-Length, huge headers, invalid UTF-8, overflow numbers, chunked
encoding, connection floods, slow-loris — against an in-test bridge
backend, asserting: no crash/hang, no wrong-but-200, and no frame
desync (a well-formed request after garbage still gets a correct
answer on a fresh connection).
"""

import asyncio
import json
import pathlib
import socket
import subprocess
import sys
import threading
import time

import pytest

from gubernator_tpu.api.types import RateLimitResp, Status
from gubernator_tpu.serve.edge_bridge import EdgeBridge

from tests._util import edge_binary

EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

PORT = 19285
SOCK = "/tmp/guber-edge-fuzz.sock"


class FakeInstance:
    """Answers every request UNDER_LIMIT with remaining = limit - hits."""

    async def get_rate_limits(self, reqs, stage_frame=False):
        return [
            RateLimitResp(
                status=Status.UNDER_LIMIT,
                limit=r.limit,
                remaining=r.limit - r.hits,
                reset_time=1700000000000,
            )
            for r in reqs
        ]


@pytest.fixture(scope="module")
def edge():
    pathlib.Path(SOCK).unlink(missing_ok=True)
    loop = asyncio.new_event_loop()
    bridge = EdgeBridge(FakeInstance(), SOCK)

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(bridge.start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    for _ in range(50):
        if pathlib.Path(SOCK).exists():
            break
        time.sleep(0.05)
    proc = subprocess.Popen(
        [str(EDGE_BIN), "--listen", str(PORT), "--backend", SOCK,
         "--batch-wait-us", "200", "--max-conns", "64",
         "--recv-timeout-s", "1"],
        stdout=sys.stderr, stderr=subprocess.STDOUT,
    )
    # wait for the edge to listen
    for _ in range(100):
        try:
            with socket.create_connection(("127.0.0.1", PORT), 0.2):
                break
        except OSError:
            time.sleep(0.05)
    else:
        proc.kill()
        raise RuntimeError("edge did not listen")
    yield proc
    proc.terminate()
    proc.wait(timeout=5)

    async def shutdown():
        await bridge.stop()
        loop.stop()

    loop.call_soon_threadsafe(lambda: loop.create_task(shutdown()))
    t.join(timeout=5)


def raw_roundtrip(data: bytes, timeout=5.0, expect_reply=True) -> bytes:
    with socket.create_connection(("127.0.0.1", PORT), timeout) as s:
        s.settimeout(timeout)
        s.sendall(data)
        buf = b""
        try:
            while True:
                b = s.recv(65536)
                if not b:
                    break
                buf += b
                hdr_end = buf.find(b"\r\n\r\n")
                if hdr_end < 0:
                    continue
                head = buf[:hdr_end].lower()
                pos = head.find(b"content-length:")
                if pos < 0:
                    break
                clen = int(head[pos + 15:].split(b"\r\n")[0])
                if len(buf) >= hdr_end + 4 + clen:
                    break
        except socket.timeout:
            if expect_reply:
                raise
        return buf


def good_request(key="ok", hits=1, limit=5) -> bytes:
    body = json.dumps({
        "requests": [
            {"name": "fz", "uniqueKey": key, "hits": hits,
             "limit": limit, "duration": 60000}
        ]
    }).encode()
    return (
        b"POST /v1/GetRateLimits HTTP/1.1\r\nContent-Length: "
        + str(len(body)).encode() + b"\r\n\r\n" + body
    )


def assert_edge_alive():
    """A clean request on a fresh connection still gets a correct 200."""
    out = raw_roundtrip(good_request())
    assert b"200 OK" in out and b"UNDER_LIMIT" in out, out


def test_clean_request_baseline(edge):
    assert_edge_alive()


def test_malformed_json_bodies(edge):
    corpus = [
        b"{",
        b"}",
        b"[]",
        b"{\"requests\": [",
        b"{\"requests\": [{]}",
        b"\x00\x01\x02\x03",
        b"{\"requests\": [{\"name\": \"a\"",
        b"{\"requests\": [{\"hits\": }]}",
        b"{\"requests\": [{\"hits\": --3}]}",
        b"{\"requests\": \"not-a-list\"}",
        b'{"requests": [{"name": "\\u12"}]}',  # truncated \\u escape
        b'{"requests": [{"name": "' + b"\xff\xfe" + b'"}]}',
    ]
    for body in corpus:
        req = (
            b"POST /v1/GetRateLimits HTTP/1.1\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        out = raw_roundtrip(req)
        # malformed JSON must 400 (or answer with per-item semantics for
        # the UTF-8 case) — never crash, never desync
        assert out.startswith(b"HTTP/1.1"), (body, out)
        assert b"200 OK" in out or b"400" in out, (body, out)
    assert_edge_alive()


def test_number_truncated_by_content_length_no_bleed(edge):
    """Content-Length cuts the body mid-number; the digits of a SECOND
    pipelined request must not be absorbed into the first (old strtoll
    bug) and the stream must stay frame-consistent."""
    body1 = b'{"requests": [{"name": "fz", "uniqueKey": "t", "hits": 12'
    req1 = (
        b"POST /v1/GetRateLimits HTTP/1.1\r\nContent-Length: "
        + str(len(body1)).encode() + b"\r\n\r\n" + body1
    )
    # pipelined second request, fully well-formed
    data = req1 + good_request(key="after-truncation")
    with socket.create_connection(("127.0.0.1", PORT), 5) as s:
        s.settimeout(5)
        s.sendall(data)
        buf = b""
        deadline = time.monotonic() + 5
        while buf.count(b"HTTP/1.1") < 2 and time.monotonic() < deadline:
            try:
                b = s.recv(65536)
            except socket.timeout:
                break
            if not b:
                break
            buf += b
    # first reply: 400 malformed; second reply: correct 200
    assert b"400" in buf, buf
    assert buf.count(b"HTTP/1.1 200") == 1 and b"UNDER_LIMIT" in buf, buf


def test_overflow_and_negative_numbers(edge):
    body = json.dumps({
        "requests": [
            {"name": "fz", "uniqueKey": "of1",
             "hits": 1, "limit": 99999999999999999999999999999,
             "duration": 60000},
            {"name": "fz", "uniqueKey": "of2", "hits": -5,
             "limit": -99999999999999999999999999999,
             "duration": 60000},
        ]
    }).encode()
    req = (
        b"POST /v1/GetRateLimits HTTP/1.1\r\nContent-Length: "
        + str(len(body)).encode() + b"\r\n\r\n" + body
    )
    out = raw_roundtrip(req)
    # saturated int64s flow through; the edge must answer 200 with two
    # items, not crash or mangle the frame
    assert b"200 OK" in out, out
    assert_edge_alive()


def test_huge_header_rejected(edge):
    data = b"POST /v1/GetRateLimits HTTP/1.1\r\nX-Filler: " + b"a" * (17 << 20)
    with socket.create_connection(("127.0.0.1", PORT), 10) as s:
        s.settimeout(10)
        try:
            s.sendall(data)
            # server should close without a reply once past the cap
            b = s.recv(4096)
            assert b == b"" or b.startswith(b"HTTP/1.1")
        except (BrokenPipeError, ConnectionResetError):
            pass  # server closed mid-send: the cap worked
    assert_edge_alive()


def test_chunked_encoding_rejected(edge):
    data = (
        b"POST /v1/GetRateLimits HTTP/1.1\r\n"
        b"Transfer-Encoding: chunked\r\n\r\n"
        b"5\r\nhello\r\n0\r\n\r\n"
    )
    out = raw_roundtrip(data)
    assert b"411" in out, out
    assert_edge_alive()


def test_oversized_body_rejected(edge):
    req = (
        b"POST /v1/GetRateLimits HTTP/1.1\r\nContent-Length: "
        + str(20 << 20).encode() + b"\r\n\r\n"
    )
    out = raw_roundtrip(req)
    assert b"413" in out, out
    assert_edge_alive()


def test_slow_loris_times_out(edge):
    """A connection that trickles an incomplete header must be closed by
    the receive timeout (--recv-timeout-s 1), not pin a thread forever."""
    with socket.create_connection(("127.0.0.1", PORT), 5) as s:
        s.settimeout(5)
        s.sendall(b"POST /v1/GetRate")
        t0 = time.monotonic()
        b = s.recv(4096)  # server closes -> b'' (or reset)
        assert b == b"", b
        assert time.monotonic() - t0 < 4
    assert_edge_alive()


def test_byte_trickle_hits_request_deadline(edge):
    """Trickling bytes fast enough to renew SO_RCVTIMEO must still be
    cut off by the per-request wall deadline (a slow-loris variant)."""
    with socket.create_connection(("127.0.0.1", PORT), 5) as s:
        s.settimeout(5)
        t0 = time.monotonic()
        closed = False
        for _ in range(12):  # one byte every 0.3s for up to 3.6s
            try:
                s.sendall(b"P")
            except (BrokenPipeError, ConnectionResetError):
                closed = True
                break
            try:
                s.settimeout(0.3)
                b = s.recv(64)
                if b == b"":
                    closed = True
                    break
            except socket.timeout:
                pass
        assert closed, "trickling client outlived the request deadline"
        assert time.monotonic() - t0 < 5
    assert_edge_alive()


def test_connection_cap(edge):
    """Connections beyond --max-conns are answered 503 and closed."""
    conns = []
    got_503 = False
    try:
        for _ in range(80):  # cap is 64
            s = socket.create_connection(("127.0.0.1", PORT), 2)
            s.settimeout(2)
            conns.append(s)
        # the newest connections should have been rejected; probe them
        for s in reversed(conns):
            try:
                b = s.recv(4096)
            except socket.timeout:
                continue
            if b"503" in b:
                got_503 = True
                break
    finally:
        for s in conns:
            s.close()
    assert got_503
    time.sleep(1.2)  # let rejected/idle conns drain before other tests
    assert_edge_alive()
