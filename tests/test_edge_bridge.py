"""Edge-bridge frame protocol unit tests (no native binary needed).

The C++ edge passes client bytes through its minimal JSON parser
verbatim, so the Python bridge is the first place invalid UTF-8 can
surface; one client's garbage must fail only its own item, never the
co-batched requests of other connections (ADVICE r1 medium).
"""

import asyncio
import struct

from gubernator_tpu.api.types import RateLimitResp, Status
from gubernator_tpu.serve.edge_bridge import (
    MAGIC_REQ,
    MAGIC_RESP,
    EdgeBridge,
    decode_request_frame,
    encode_response_frame,
)


def _item(name: bytes, key: bytes, hits=1, limit=5, duration=1000,
          algo=0, behavior=0) -> bytes:
    return (
        struct.pack("<H", len(name)) + name
        + struct.pack("<H", len(key)) + key
        + struct.pack("<qqqBB", hits, limit, duration, algo, behavior)
    )


def _frame(items) -> bytes:
    payload = b"".join(items)
    return struct.pack("<II", MAGIC_REQ, len(items)) + struct.pack(
        "<I", len(payload)
    ) + payload


BAD = b"\xff\xfe\x80"  # not valid UTF-8


def test_decode_isolates_invalid_utf8_items():
    items = [
        _item(b"api", b"good-1"),
        _item(b"api", BAD),
        _item(BAD, b"good-key"),
        _item(b"api", b"good-2"),
    ]
    payload = b"".join(items)
    decoded = decode_request_frame(payload, 4)
    assert decoded[0] is not None and decoded[0].unique_key == "good-1"
    assert decoded[1] is None
    assert decoded[2] is None
    assert decoded[3] is not None and decoded[3].unique_key == "good-2"


def test_bridge_answers_bad_item_without_failing_frame():
    """A frame mixing a bad-UTF-8 item with good ones must answer ALL
    items: per-item error for the bad one, real decisions for the rest."""

    class FakeInstance:
        async def get_rate_limits(self, reqs):
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=r.limit,
                    remaining=r.limit - r.hits, reset_time=123,
                )
                for r in reqs
            ]

    async def run():
        path = "/tmp/guber-bridge-utf8-test.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            # capability hello comes first on every connection (r4)
            from gubernator_tpu.serve.edge_bridge import MAGIC_HELLO

            hmagic, _flags = struct.unpack(
                "<II", await reader.readexactly(8)
            )
            assert hmagic == MAGIC_HELLO
            writer.write(_frame([
                _item(b"api", b"ok-1"),
                _item(b"api", BAD),
                _item(b"api", b"ok-2"),
            ]))
            await writer.drain()
            magic, n = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_RESP and n == 3
            out = []
            for _ in range(n):
                st, limit, rem, reset = struct.unpack(
                    "<Bqqq", await reader.readexactly(25)
                )
                (elen,) = struct.unpack("<H", await reader.readexactly(2))
                err = (await reader.readexactly(elen)).decode()
                (olen,) = struct.unpack("<H", await reader.readexactly(2))
                await reader.readexactly(olen)  # owner (unused here)
                out.append((st, limit, rem, reset, err))
            writer.close()
            return out
        finally:
            await bridge.stop()

    out = asyncio.run(run())
    assert out[0] == (0, 5, 4, 123, "")
    assert out[2] == (0, 5, 4, 123, "")
    assert "UTF-8" in out[1][4] and out[1][1] == 0


def test_response_roundtrip():
    resps = [
        RateLimitResp(status=Status.OVER_LIMIT, limit=9, remaining=0,
                      reset_time=42, error="boom",
                      metadata={"owner": "10.0.0.3:81"}),
    ]
    raw = encode_response_frame(resps)
    magic, n = struct.unpack_from("<II", raw)
    assert magic == MAGIC_RESP and n == 1
    st, limit, rem, reset = struct.unpack_from("<Bqqq", raw, 8)
    assert (st, limit, rem, reset) == (1, 9, 0, 42)
    off = 8 + 25
    (elen,) = struct.unpack_from("<H", raw, off)
    assert raw[off + 2 : off + 2 + elen] == b"boom"
    off += 2 + elen
    (olen,) = struct.unpack_from("<H", raw, off)
    assert raw[off + 2 : off + 2 + olen] == b"10.0.0.3:81"


def test_fast_frame_chunks_oversized_batches():
    """A GEB4 frame beyond MAX_BATCH_SIZE must reach the batcher as
    ladder-sized chunks (the engine's compiled rungs top out there), and
    the concatenated responses must preserve request order."""
    import numpy as np

    from gubernator_tpu.serve.config import MAX_BATCH_SIZE
    from gubernator_tpu.serve.edge_bridge import (
        MAGIC_FAST_REQ,
        MAGIC_FAST_RESP,
        MAGIC_HELLO,
        _fast_dtypes,
    )

    seen_sizes = []

    class FakeBatcher:
        async def decide_arrays(self, fields):
            n = fields["key_hash"].shape[0]
            seen_sizes.append(n)
            # echo limit back as remaining so order is checkable
            return (
                np.zeros(n, np.int64),
                fields["limit"],
                fields["limit"],
                np.zeros(n, np.int64),
            )

    class FakeBackend:
        decide_submit_arrays = object()
        decide_submit = object()

    class FakePicker:
        # live membership, the surface _fast_ok actually consults
        def peers(self):
            return ["self"]

    class FakeTraffic:
        def observe_hashes(self, h):
            pass

    class FakeInstance:
        backend = FakeBackend()
        picker = FakePicker()
        batcher = FakeBatcher()
        traffic = FakeTraffic()

    async def run():
        path = "/tmp/guber-bridge-fast-chunk.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            hmagic, flags = struct.unpack(
                "<II", await reader.readexactly(8)
            )
            assert hmagic == MAGIC_HELLO and flags == 1
            n = MAX_BATCH_SIZE + 500
            req_dt, resp_dt = _fast_dtypes()
            rec = np.empty(n, req_dt)
            rec["key_hash"] = np.arange(1, n + 1, dtype=np.uint64)
            rec["hits"] = 1
            rec["limit"] = np.arange(n, dtype=np.int64)
            rec["duration"] = 1000
            rec["algo"] = 0
            payload = rec.tobytes()
            writer.write(
                struct.pack("<II", MAGIC_FAST_REQ, n)
                + struct.pack("<I", len(payload))
                + payload
            )
            await writer.drain()
            magic, rn = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_FAST_RESP and rn == n
            out = np.frombuffer(
                await reader.readexactly(n * resp_dt.itemsize), resp_dt
            )
            writer.close()
            return out
        finally:
            await bridge.stop()

    out = asyncio.run(run())
    assert seen_sizes == [MAX_BATCH_SIZE, 500]
    assert (out["remaining"] == np.arange(MAX_BATCH_SIZE + 500)).all()


def test_fast_path_disabled_when_membership_grows():
    """The GEB4 fast path bypasses ring routing, so LIVE membership
    (picker.peers(), which discovery updates via set_peers) must gate
    it — not static config. With >1 peers the hello advertises slow
    path, and a GEB4 frame sent anyway is refused (connection closed),
    never silently decided locally (r4 review: ~Nx over-admission)."""
    import numpy as np

    from gubernator_tpu.serve.edge_bridge import (
        MAGIC_FAST_REQ,
        MAGIC_HELLO,
        _fast_dtypes,
    )

    class FakeBackend:
        decide_submit_arrays = object()
        decide_submit = object()

    class FakePicker:
        def peers(self):
            return ["self", "other"]  # grown cluster

    class FakeInstance:
        backend = FakeBackend()
        picker = FakePicker()

    async def run():
        path = "/tmp/guber-bridge-fast-multinode.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            hmagic, flags = struct.unpack(
                "<II", await reader.readexactly(8)
            )
            assert hmagic == MAGIC_HELLO and flags == 0
            # a (buggy or stale) edge sends GEB4 anyway: refused loudly
            req_dt, _ = _fast_dtypes()
            rec = np.zeros(2, req_dt)
            rec["key_hash"] = [1, 2]
            payload = rec.tobytes()
            writer.write(
                struct.pack("<II", MAGIC_FAST_REQ, 2)
                + struct.pack("<I", len(payload))
                + payload
            )
            await writer.drain()
            got = await reader.read(8)
            assert got == b"", got  # connection closed, no response
            writer.close()
        finally:
            await bridge.stop()

    asyncio.run(run())
