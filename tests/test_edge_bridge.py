"""Edge-bridge frame protocol unit tests (no native binary needed).

The C++ edge passes client bytes through its minimal JSON parser
verbatim, so the Python bridge is the first place invalid UTF-8 can
surface; one client's garbage must fail only its own item, never the
co-batched requests of other connections (ADVICE r1 medium).

r5: the hello carries the cluster ring ('GEBI') and pre-hashed frames
('GEB6') echo the membership fingerprint they were routed with; a
frame routed under a different view is refused with 'GEBR' — the
over-admission guard that replaced r4's single-node gate.
"""

import asyncio
import struct
from dataclasses import dataclass

from gubernator_tpu.api.types import RateLimitResp, Status
from gubernator_tpu.serve.edge_bridge import (
    HELLO_FAST,
    HELLO_WINDOWED,
    MAGIC_REQ,
    MAGIC_RESP,
    EdgeBridge,
    decode_request_frame,
    encode_response_frame,
    ring_fingerprint,
)


def _item(name: bytes, key: bytes, hits=1, limit=5, duration=1000,
          algo=0, behavior=0) -> bytes:
    return (
        struct.pack("<H", len(name)) + name
        + struct.pack("<H", len(key)) + key
        + struct.pack("<qqqBB", hits, limit, duration, algo, behavior)
    )


def _frame(items) -> bytes:
    payload = b"".join(items)
    return struct.pack("<II", MAGIC_REQ, len(items)) + struct.pack(
        "<I", len(payload)
    ) + payload


async def _read_hello(reader):
    """Parse the GEBI hello; returns (flags, ring_hash, nodes) where
    nodes is a list of (is_self, grpc, bridge)."""
    from gubernator_tpu.serve.edge_bridge import MAGIC_HELLO

    magic, flags, rhash, n = struct.unpack(
        "<IIII", await reader.readexactly(16)
    )
    assert magic == MAGIC_HELLO
    nodes = []
    for _ in range(n):
        is_self, glen = struct.unpack("<BH", await reader.readexactly(3))
        grpc = (await reader.readexactly(glen)).decode()
        (blen,) = struct.unpack("<H", await reader.readexactly(2))
        bridge = (await reader.readexactly(blen)).decode()
        nodes.append((bool(is_self), grpc, bridge))
    return flags, rhash, nodes


@dataclass
class FakePeer:
    host: str
    is_owner: bool = False


BAD = b"\xff\xfe\x80"  # not valid UTF-8


def test_decode_isolates_invalid_utf8_items():
    items = [
        _item(b"api", b"good-1"),
        _item(b"api", BAD),
        _item(BAD, b"good-key"),
        _item(b"api", b"good-2"),
    ]
    payload = b"".join(items)
    decoded = decode_request_frame(payload, 4)
    assert decoded[0] is not None and decoded[0].unique_key == "good-1"
    assert decoded[1] is None
    assert decoded[2] is None
    assert decoded[3] is not None and decoded[3].unique_key == "good-2"


def test_bridge_answers_bad_item_without_failing_frame():
    """A frame mixing a bad-UTF-8 item with good ones must answer ALL
    items: per-item error for the bad one, real decisions for the rest."""

    class FakeInstance:
        async def get_rate_limits(self, reqs, stage_frame=False):
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=r.limit,
                    remaining=r.limit - r.hits, reset_time=123,
                )
                for r in reqs
            ]

    async def run():
        path = "/tmp/guber-bridge-utf8-test.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            await _read_hello(reader)
            writer.write(_frame([
                _item(b"api", b"ok-1"),
                _item(b"api", BAD),
                _item(b"api", b"ok-2"),
            ]))
            await writer.drain()
            magic, n = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_RESP and n == 3
            out = []
            for _ in range(n):
                st, limit, rem, reset = struct.unpack(
                    "<Bqqq", await reader.readexactly(25)
                )
                (elen,) = struct.unpack("<H", await reader.readexactly(2))
                err = (await reader.readexactly(elen)).decode()
                (olen,) = struct.unpack("<H", await reader.readexactly(2))
                await reader.readexactly(olen)  # owner (unused here)
                out.append((st, limit, rem, reset, err))
            writer.close()
            return out
        finally:
            await bridge.stop()

    out = asyncio.run(run())
    assert out[0] == (0, 5, 4, 123, "")
    assert out[2] == (0, 5, 4, 123, "")
    assert "UTF-8" in out[1][4] and out[1][1] == 0


def test_response_roundtrip():
    resps = [
        RateLimitResp(status=Status.OVER_LIMIT, limit=9, remaining=0,
                      reset_time=42, error="boom",
                      metadata={"owner": "10.0.0.3:81"}),
    ]
    raw = encode_response_frame(resps)
    magic, n = struct.unpack_from("<II", raw)
    assert magic == MAGIC_RESP and n == 1
    st, limit, rem, reset = struct.unpack_from("<Bqqq", raw, 8)
    assert (st, limit, rem, reset) == (1, 9, 0, 42)
    off = 8 + 25
    (elen,) = struct.unpack_from("<H", raw, off)
    assert raw[off + 2 : off + 2 + elen] == b"boom"
    off += 2 + elen
    (olen,) = struct.unpack_from("<H", raw, off)
    assert raw[off + 2 : off + 2 + olen] == b"10.0.0.3:81"


class _FakeBackendArrays:
    decide_submit_arrays = object()
    decide_submit = object()


class _FakeTraffic:
    def observe_hashes(self, h):
        pass

    def observe(self, keys, hashes):
        pass


def _fast_frame(rec, ring_hash):
    from gubernator_tpu.serve.edge_bridge import MAGIC_FAST_REQ

    payload = rec.tobytes()
    return (
        struct.pack("<II", MAGIC_FAST_REQ, len(rec))
        + struct.pack("<II", ring_hash, len(payload))
        + payload
    )


def test_fast_frame_chunks_oversized_batches():
    """A GEB6 frame beyond MAX_BATCH_SIZE must reach the batcher as
    ladder-sized chunks (the engine's compiled rungs top out there), and
    the concatenated responses must preserve request order."""
    import numpy as np

    from gubernator_tpu.serve.config import MAX_BATCH_SIZE
    from gubernator_tpu.serve.edge_bridge import (
        MAGIC_FAST_RESP,
        _fast_dtypes,
    )

    seen_sizes = []

    class FakeBatcher:
        async def decide_arrays(self, fields, frame=True):
            n = fields["key_hash"].shape[0]
            seen_sizes.append(n)
            # echo limit back as remaining so order is checkable
            return (
                np.zeros(n, np.int64),
                fields["limit"],
                fields["limit"],
                np.zeros(n, np.int64),
            )

    class FakePicker:
        # live membership, the surface the hello actually consults
        def peers(self):
            return [FakePeer("127.0.0.1:81", is_owner=True)]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()
        batcher = FakeBatcher()
        traffic = _FakeTraffic()

    async def run():
        path = "/tmp/guber-bridge-fast-chunk.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, rhash, nodes = await _read_hello(reader)
            assert flags & HELLO_FAST
            assert flags & HELLO_WINDOWED  # r7: windowed frames accepted
            assert (flags >> 16) >= 1  # advertised credit window
            assert rhash == ring_fingerprint(["127.0.0.1:81"])
            assert nodes == [(True, "127.0.0.1:81", "")]
            n = MAX_BATCH_SIZE + 500
            req_dt, resp_dt = _fast_dtypes()
            rec = np.empty(n, req_dt)
            rec["key_hash"] = np.arange(1, n + 1, dtype=np.uint64)
            rec["hits"] = 1
            rec["limit"] = np.arange(n, dtype=np.int64)
            rec["duration"] = 1000
            rec["algo"] = 0
            writer.write(_fast_frame(rec, rhash))
            await writer.drain()
            magic, rn = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_FAST_RESP and rn == n
            out = np.frombuffer(
                await reader.readexactly(n * resp_dt.itemsize), resp_dt
            )
            writer.close()
            return out
        finally:
            await bridge.stop()

    out = asyncio.run(run())
    assert seen_sizes == [MAX_BATCH_SIZE, 500]
    assert (out["remaining"] == np.arange(MAX_BATCH_SIZE + 500)).all()


def test_multinode_hello_carries_ring_and_bridge_endpoints():
    """With >1 peers and a TCP listener configured, the hello must
    advertise the fast path plus every node's bridge endpoint (peer
    gRPC host + this node's TCP port — the symmetric-fleet convention),
    with an empty endpoint for self (the edge uses its --backend)."""

    class FakePicker:
        def peers(self):
            return [
                FakePeer("10.0.0.2:81"),
                FakePeer("10.0.0.1:81", is_owner=True),
            ]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()

    async def run():
        path = "/tmp/guber-bridge-ring-hello.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        # set after start: only the hello's endpoint derivation reads
        # it here; the real TCP listener is covered by the cluster e2e
        # (tests/test_edge_cluster.py)
        bridge.tcp_address = "0.0.0.0:9470"
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, rhash, nodes = await _read_hello(reader)
            writer.close()
            return flags, rhash, nodes
        finally:
            await bridge.stop()

    flags, rhash, nodes = asyncio.run(run())
    assert flags & HELLO_FAST  # fast path stays on in a cluster (r5)
    assert rhash == ring_fingerprint(["10.0.0.1:81", "10.0.0.2:81"])
    # sorted by gRPC address; self has no bridge endpoint, the peer's is
    # derived from its host + our TCP port
    assert nodes == [
        (True, "10.0.0.1:81", ""),
        (False, "10.0.0.2:81", "10.0.0.2:9470"),
    ]


def test_stale_ring_fast_frame_refused_with_gebr():
    """A GEB6 frame whose ring fingerprint does not match the live
    membership must be answered with GEBR and the connection closed —
    deciding it locally could admit keys this node no longer owns
    (the r5 replacement for r4's fast-path-off-in-clusters gate)."""
    import numpy as np

    from gubernator_tpu.serve.edge_bridge import MAGIC_STALE, _fast_dtypes

    class FakePicker:
        def peers(self):
            return [
                FakePeer("10.0.0.1:81", is_owner=True),
                FakePeer("10.0.0.2:81"),
            ]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()
        traffic = _FakeTraffic()

    async def run():
        path = "/tmp/guber-bridge-stale-ring.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, rhash, _nodes = await _read_hello(reader)
            assert flags & HELLO_FAST
            req_dt, _ = _fast_dtypes()
            rec = np.zeros(2, req_dt)
            rec["key_hash"] = [1, 2]
            stale = (rhash + 1) & 0xFFFFFFFF
            writer.write(_fast_frame(rec, stale))
            await writer.drain()
            magic, n = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_STALE and n == 0
            got = await reader.read(8)
            assert got == b"", got  # bridge closed after GEBR
            writer.close()
        finally:
            await bridge.stop()

    asyncio.run(run())


def _witem_frame(frame_id: int, items, t_sent_us: int = 0) -> bytes:
    """Windowed string request (GEB2): frame_id + monotonic stamp."""
    from gubernator_tpu.serve.edge_bridge import MAGIC_WREQ

    payload = b"".join(items)
    return (
        struct.pack("<II", MAGIC_WREQ, len(items))
        + struct.pack("<IQ", frame_id, t_sent_us)
        + struct.pack("<I", len(payload))
        + payload
    )


async def _read_wresp(reader):
    """One GEB4 windowed response: (frame_id, [(status, limit, rem,
    reset, error, owner)])."""
    from gubernator_tpu.serve.edge_bridge import MAGIC_WRESP

    magic, n = struct.unpack("<II", await reader.readexactly(8))
    assert magic == MAGIC_WRESP, hex(magic)
    (fid,) = struct.unpack("<I", await reader.readexactly(4))
    out = []
    for _ in range(n):
        st, limit, rem, reset = struct.unpack(
            "<Bqqq", await reader.readexactly(25)
        )
        (elen,) = struct.unpack("<H", await reader.readexactly(2))
        err = (await reader.readexactly(elen)).decode()
        (olen,) = struct.unpack("<H", await reader.readexactly(2))
        owner = (await reader.readexactly(olen)).decode()
        out.append((st, limit, rem, reset, err, owner))
    return fid, out


def test_windowed_frames_complete_out_of_order():
    """Two GEB2 frames in flight on one connection: the first is served
    slowly, the second fast — the responses must come back second-first,
    matched by frame id. Out-of-order completion IS the pipelining win:
    a slow frame no longer convoys the frames behind it."""
    import time as _time

    release_slow = asyncio.Event()

    class FakeInstance:
        async def get_rate_limits(self, reqs, stage_frame=False):
            if reqs[0].unique_key == "slow":
                await release_slow.wait()
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=r.limit,
                    remaining=r.limit - r.hits, reset_time=7,
                )
                for r in reqs
            ]

    async def run():
        path = "/tmp/guber-bridge-windowed-ooo.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            await _read_hello(reader)
            t_us = int(_time.monotonic() * 1e6)
            writer.write(_witem_frame(11, [_item(b"api", b"slow")], t_us))
            writer.write(_witem_frame(12, [_item(b"api", b"fast")], t_us))
            await writer.drain()
            first = await asyncio.wait_for(_read_wresp(reader), 5)
            release_slow.set()
            second = await asyncio.wait_for(_read_wresp(reader), 5)
            writer.close()
            return first, second
        finally:
            await bridge.stop()

    (fid1, resp1), (fid2, resp2) = asyncio.run(run())
    assert fid1 == 12  # the fast frame finished first
    assert fid2 == 11
    assert resp1[0][:4] == (0, 5, 4, 7)
    assert resp2[0][:4] == (0, 5, 4, 7)


def test_windowed_credit_exhaustion_backpressures_reads():
    """With window=2 and the instance gated shut, only the first two
    frames may reach the instance — the bridge must stop READING the
    connection (credit acquired before the next frame read) so TCP
    backpressure, not a drop or an error, polices an edge overrunning
    its credit. Opening the gate completes all four frames."""
    gate = asyncio.Event()
    calls = []

    class FakeInstance:
        async def get_rate_limits(self, reqs, stage_frame=False):
            calls.append(reqs[0].unique_key)
            await gate.wait()
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=r.limit,
                    remaining=r.limit - r.hits, reset_time=1,
                )
                for r in reqs
            ]

    async def run():
        path = "/tmp/guber-bridge-windowed-credit.sock"
        bridge = EdgeBridge(FakeInstance(), path, window=2)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, _rhash, _nodes = await _read_hello(reader)
            assert flags >> 16 == 2  # the advertised window
            for fid in range(1, 5):
                writer.write(
                    _witem_frame(fid, [_item(b"api", b"k%d" % fid)])
                )
            await writer.drain()
            await asyncio.sleep(0.3)
            # credit window exhausted after two in-flight frames: the
            # bridge must not have started serving frames 3 and 4
            blocked_calls = list(calls)
            gate.set()
            fids = set()
            for _ in range(4):
                fid, resps = await asyncio.wait_for(_read_wresp(reader), 5)
                fids.add(fid)
                assert resps[0][0] == 0
            writer.close()
            return blocked_calls, fids
        finally:
            await bridge.stop()

    blocked_calls, fids = asyncio.run(run())
    assert len(blocked_calls) == 2, blocked_calls
    assert fids == {1, 2, 3, 4}


def test_windowed_stale_ring_refused_mid_window():
    """A GEB7 fast frame routed with a stale membership fingerprint must
    be refused with GEBR carrying ITS frame id — even while other
    frames are still in flight on the window — and the connection
    closed (every outstanding frame was routed with the same stale
    view; the edge fails them stale and re-reads the ring)."""
    import numpy as np

    from gubernator_tpu.serve.edge_bridge import (
        MAGIC_STALE,
        MAGIC_WFAST_REQ,
        _fast_dtypes,
    )

    gate = asyncio.Event()

    class FakeBatcher:
        async def decide_arrays(self, fields, frame=True):
            await gate.wait()  # frame 1 parks here, mid-window
            n = fields["key_hash"].shape[0]
            return (
                np.zeros(n, np.int64),
                fields["limit"],
                fields["limit"],
                np.zeros(n, np.int64),
            )

    class FakePicker:
        def peers(self):
            return [FakePeer("127.0.0.1:81", is_owner=True)]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()
        batcher = FakeBatcher()
        traffic = _FakeTraffic()

    def wfast(fid, rec, ring_hash):
        payload = rec.tobytes()
        return (
            struct.pack("<II", MAGIC_WFAST_REQ, len(rec))
            + struct.pack("<IIQ", fid, ring_hash, 0)
            + struct.pack("<I", len(payload))
            + payload
        )

    async def run():
        path = "/tmp/guber-bridge-windowed-stale.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            _flags, rhash, _nodes = await _read_hello(reader)
            req_dt, _ = _fast_dtypes()
            rec = np.zeros(1, req_dt)
            rec["key_hash"] = [1]
            rec["limit"] = [5]
            writer.write(wfast(21, rec, rhash))  # parks in the batcher
            writer.write(wfast(22, rec, (rhash + 1) & 0xFFFFFFFF))
            await writer.drain()
            magic, fid = struct.unpack(
                "<II", await asyncio.wait_for(reader.readexactly(8), 5)
            )
            assert magic == MAGIC_STALE and fid == 22
            got = await asyncio.wait_for(reader.read(8), 5)
            assert got == b"", got  # connection closed after GEBR
            writer.close()
        finally:
            await bridge.stop()

    asyncio.run(run())


def test_fast_kill_switch_unadvertises():
    """GUBER_EDGE_FAST=0 (EdgeBridge fast_enabled=False) must stop
    advertising the pre-hashed path in the hello — the operational
    fallback that forces every edge item through the full instance."""

    class FakePicker:
        def peers(self):
            return [FakePeer("127.0.0.1:81", is_owner=True)]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()

    async def run():
        path = "/tmp/guber-bridge-killswitch.sock"
        bridge = EdgeBridge(FakeInstance(), path, fast_enabled=False)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, _rhash, _nodes = await _read_hello(reader)
            writer.close()
            return flags
        finally:
            await bridge.stop()

    flags = asyncio.run(run())
    assert not (flags & HELLO_FAST)
    assert flags & HELLO_WINDOWED  # windowed framing is fast-agnostic


def _fold_fixture(is_owner: bool, string_fold: bool = True,
                  fast_enabled: bool = True):
    """Bridge over a real ConsistentHashPicker (one peer) whose batcher
    and instance record which path served each frame."""
    import numpy as np

    from gubernator_tpu.serve.peers import ConsistentHashPicker

    folded_sizes = []
    object_path_keys = []

    class FakeBatcher:
        async def decide_arrays(self, fields, frame=True):
            n = fields["key_hash"].shape[0]
            folded_sizes.append(n)
            return (
                np.zeros(n, np.int64),
                fields["limit"],
                fields["limit"] - fields["hits"],
                np.full(n, 77, np.int64),
            )

    class FakeInstance:
        backend = _FakeBackendArrays()
        traffic = _FakeTraffic()
        batcher = FakeBatcher()
        picker = ConsistentHashPicker()

        async def get_rate_limits(self, reqs, stage_frame=False):
            object_path_keys.extend(r.unique_key for r in reqs)
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=r.limit,
                    remaining=r.limit - r.hits, reset_time=77,
                )
                for r in reqs
            ]

    inst = FakeInstance()
    inst.picker.add(FakePeer("127.0.0.1:81", is_owner=is_owner))
    bridge = EdgeBridge(
        inst, "", fast_enabled=fast_enabled, string_fold=string_fold
    )
    return bridge, folded_sizes, object_path_keys


def _roundtrip_string_frame(bridge, items, sock_name):
    """Send one GEB1 frame through a started bridge; return the decoded
    per-item responses."""

    async def run():
        path = f"/tmp/guber-bridge-{sock_name}.sock"
        bridge.path = path
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            await _read_hello(reader)
            writer.write(_frame(items))
            await writer.drain()
            magic, n = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_RESP and n == len(items)
            out = []
            for _ in range(n):
                st, limit, rem, reset = struct.unpack(
                    "<Bqqq", await reader.readexactly(25)
                )
                (elen,) = struct.unpack("<H", await reader.readexactly(2))
                err = (await reader.readexactly(elen)).decode()
                (olen,) = struct.unpack("<H", await reader.readexactly(2))
                owner = (await reader.readexactly(olen)).decode()
                out.append((st, limit, rem, reset, err, owner))
            writer.close()
            return out
        finally:
            await bridge.stop()

    return asyncio.run(run())


def test_string_fold_serves_plain_owned_frame_via_arrays():
    """An all-plain all-owned GEB1 frame must skip the instance and
    ride the array path (r7 string->array fold), producing wire bytes
    identical in layout to the object path: 25-byte decisions with
    empty error and owner fields. The fold must work with the fast
    kill switch thrown — that is the case it exists for."""
    bridge, folded_sizes, object_path_keys = _fold_fixture(
        is_owner=True, fast_enabled=False
    )
    out = _roundtrip_string_frame(
        bridge,
        [_item(b"api", b"k1", hits=1, limit=5),
         _item(b"api", b"k2", hits=2, limit=9)],
        "fold-owned",
    )
    assert folded_sizes == [2]
    assert object_path_keys == []
    assert out[0] == (0, 5, 4, 77, "", "")
    assert out[1] == (0, 9, 7, 77, "", "")


def test_string_fold_declines_global_and_unowned_frames():
    """A GLOBAL item anywhere in the frame, or any key this node does
    not own, must push the WHOLE frame onto the object path — the fold
    never bypasses global-manager or forwarding semantics."""
    bridge, folded_sizes, object_path_keys = _fold_fixture(is_owner=True)
    out = _roundtrip_string_frame(
        bridge,
        [_item(b"api", b"k1"), _item(b"api", b"g1", behavior=2)],
        "fold-global",
    )
    assert folded_sizes == []
    assert object_path_keys == ["k1", "g1"]
    assert out[0][:4] == (0, 5, 4, 77)

    bridge, folded_sizes, object_path_keys = _fold_fixture(is_owner=False)
    _roundtrip_string_frame(bridge, [_item(b"api", b"k1")], "fold-unowned")
    assert folded_sizes == []
    assert object_path_keys == ["k1"]


def test_string_fold_kill_switch():
    """GUBER_EDGE_STRING_FOLD=0 (string_fold=False) must restore the
    pre-r7 all-objects string path even for foldable frames."""
    bridge, folded_sizes, object_path_keys = _fold_fixture(
        is_owner=True, string_fold=False
    )
    _roundtrip_string_frame(bridge, [_item(b"api", b"k1")], "fold-off")
    assert folded_sizes == []
    assert object_path_keys == ["k1"]


def test_picker_self_owned_mask_matches_get():
    """self_owned_mask (the fold's vectorized ownership screen) must
    agree with get() — the authoritative per-key placement — across a
    multi-peer ring."""
    from gubernator_tpu.serve.peers import ConsistentHashPicker

    picker = ConsistentHashPicker()
    picker.add(FakePeer("10.0.0.1:81", is_owner=True))
    picker.add(FakePeer("10.0.0.2:81"))
    picker.add(FakePeer("10.0.0.3:81"))
    keys = [f"api_k{i}" for i in range(500)]
    mask = picker.self_owned_mask(keys)
    assert mask.any() and not mask.all()  # 500 keys spread over 3 peers
    for k, owned in zip(keys, mask):
        assert picker.get(k).is_owner == bool(owned)
