"""Edge-bridge frame protocol unit tests (no native binary needed).

The C++ edge passes client bytes through its minimal JSON parser
verbatim, so the Python bridge is the first place invalid UTF-8 can
surface; one client's garbage must fail only its own item, never the
co-batched requests of other connections (ADVICE r1 medium).

r5: the hello carries the cluster ring ('GEBI') and pre-hashed frames
('GEB6') echo the membership fingerprint they were routed with; a
frame routed under a different view is refused with 'GEBR' — the
over-admission guard that replaced r4's single-node gate.
"""

import asyncio
import struct
from dataclasses import dataclass

from gubernator_tpu.api.types import RateLimitResp, Status
from gubernator_tpu.serve.edge_bridge import (
    MAGIC_REQ,
    MAGIC_RESP,
    EdgeBridge,
    decode_request_frame,
    encode_response_frame,
    ring_fingerprint,
)


def _item(name: bytes, key: bytes, hits=1, limit=5, duration=1000,
          algo=0, behavior=0) -> bytes:
    return (
        struct.pack("<H", len(name)) + name
        + struct.pack("<H", len(key)) + key
        + struct.pack("<qqqBB", hits, limit, duration, algo, behavior)
    )


def _frame(items) -> bytes:
    payload = b"".join(items)
    return struct.pack("<II", MAGIC_REQ, len(items)) + struct.pack(
        "<I", len(payload)
    ) + payload


async def _read_hello(reader):
    """Parse the GEBI hello; returns (flags, ring_hash, nodes) where
    nodes is a list of (is_self, grpc, bridge)."""
    from gubernator_tpu.serve.edge_bridge import MAGIC_HELLO

    magic, flags, rhash, n = struct.unpack(
        "<IIII", await reader.readexactly(16)
    )
    assert magic == MAGIC_HELLO
    nodes = []
    for _ in range(n):
        is_self, glen = struct.unpack("<BH", await reader.readexactly(3))
        grpc = (await reader.readexactly(glen)).decode()
        (blen,) = struct.unpack("<H", await reader.readexactly(2))
        bridge = (await reader.readexactly(blen)).decode()
        nodes.append((bool(is_self), grpc, bridge))
    return flags, rhash, nodes


@dataclass
class FakePeer:
    host: str
    is_owner: bool = False


BAD = b"\xff\xfe\x80"  # not valid UTF-8


def test_decode_isolates_invalid_utf8_items():
    items = [
        _item(b"api", b"good-1"),
        _item(b"api", BAD),
        _item(BAD, b"good-key"),
        _item(b"api", b"good-2"),
    ]
    payload = b"".join(items)
    decoded = decode_request_frame(payload, 4)
    assert decoded[0] is not None and decoded[0].unique_key == "good-1"
    assert decoded[1] is None
    assert decoded[2] is None
    assert decoded[3] is not None and decoded[3].unique_key == "good-2"


def test_bridge_answers_bad_item_without_failing_frame():
    """A frame mixing a bad-UTF-8 item with good ones must answer ALL
    items: per-item error for the bad one, real decisions for the rest."""

    class FakeInstance:
        async def get_rate_limits(self, reqs):
            return [
                RateLimitResp(
                    status=Status.UNDER_LIMIT, limit=r.limit,
                    remaining=r.limit - r.hits, reset_time=123,
                )
                for r in reqs
            ]

    async def run():
        path = "/tmp/guber-bridge-utf8-test.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            await _read_hello(reader)
            writer.write(_frame([
                _item(b"api", b"ok-1"),
                _item(b"api", BAD),
                _item(b"api", b"ok-2"),
            ]))
            await writer.drain()
            magic, n = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_RESP and n == 3
            out = []
            for _ in range(n):
                st, limit, rem, reset = struct.unpack(
                    "<Bqqq", await reader.readexactly(25)
                )
                (elen,) = struct.unpack("<H", await reader.readexactly(2))
                err = (await reader.readexactly(elen)).decode()
                (olen,) = struct.unpack("<H", await reader.readexactly(2))
                await reader.readexactly(olen)  # owner (unused here)
                out.append((st, limit, rem, reset, err))
            writer.close()
            return out
        finally:
            await bridge.stop()

    out = asyncio.run(run())
    assert out[0] == (0, 5, 4, 123, "")
    assert out[2] == (0, 5, 4, 123, "")
    assert "UTF-8" in out[1][4] and out[1][1] == 0


def test_response_roundtrip():
    resps = [
        RateLimitResp(status=Status.OVER_LIMIT, limit=9, remaining=0,
                      reset_time=42, error="boom",
                      metadata={"owner": "10.0.0.3:81"}),
    ]
    raw = encode_response_frame(resps)
    magic, n = struct.unpack_from("<II", raw)
    assert magic == MAGIC_RESP and n == 1
    st, limit, rem, reset = struct.unpack_from("<Bqqq", raw, 8)
    assert (st, limit, rem, reset) == (1, 9, 0, 42)
    off = 8 + 25
    (elen,) = struct.unpack_from("<H", raw, off)
    assert raw[off + 2 : off + 2 + elen] == b"boom"
    off += 2 + elen
    (olen,) = struct.unpack_from("<H", raw, off)
    assert raw[off + 2 : off + 2 + olen] == b"10.0.0.3:81"


class _FakeBackendArrays:
    decide_submit_arrays = object()
    decide_submit = object()


class _FakeTraffic:
    def observe_hashes(self, h):
        pass


def _fast_frame(rec, ring_hash):
    from gubernator_tpu.serve.edge_bridge import MAGIC_FAST_REQ

    payload = rec.tobytes()
    return (
        struct.pack("<II", MAGIC_FAST_REQ, len(rec))
        + struct.pack("<II", ring_hash, len(payload))
        + payload
    )


def test_fast_frame_chunks_oversized_batches():
    """A GEB6 frame beyond MAX_BATCH_SIZE must reach the batcher as
    ladder-sized chunks (the engine's compiled rungs top out there), and
    the concatenated responses must preserve request order."""
    import numpy as np

    from gubernator_tpu.serve.config import MAX_BATCH_SIZE
    from gubernator_tpu.serve.edge_bridge import (
        MAGIC_FAST_RESP,
        _fast_dtypes,
    )

    seen_sizes = []

    class FakeBatcher:
        async def decide_arrays(self, fields):
            n = fields["key_hash"].shape[0]
            seen_sizes.append(n)
            # echo limit back as remaining so order is checkable
            return (
                np.zeros(n, np.int64),
                fields["limit"],
                fields["limit"],
                np.zeros(n, np.int64),
            )

    class FakePicker:
        # live membership, the surface the hello actually consults
        def peers(self):
            return [FakePeer("127.0.0.1:81", is_owner=True)]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()
        batcher = FakeBatcher()
        traffic = _FakeTraffic()

    async def run():
        path = "/tmp/guber-bridge-fast-chunk.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, rhash, nodes = await _read_hello(reader)
            assert flags == 1
            assert rhash == ring_fingerprint(["127.0.0.1:81"])
            assert nodes == [(True, "127.0.0.1:81", "")]
            n = MAX_BATCH_SIZE + 500
            req_dt, resp_dt = _fast_dtypes()
            rec = np.empty(n, req_dt)
            rec["key_hash"] = np.arange(1, n + 1, dtype=np.uint64)
            rec["hits"] = 1
            rec["limit"] = np.arange(n, dtype=np.int64)
            rec["duration"] = 1000
            rec["algo"] = 0
            writer.write(_fast_frame(rec, rhash))
            await writer.drain()
            magic, rn = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_FAST_RESP and rn == n
            out = np.frombuffer(
                await reader.readexactly(n * resp_dt.itemsize), resp_dt
            )
            writer.close()
            return out
        finally:
            await bridge.stop()

    out = asyncio.run(run())
    assert seen_sizes == [MAX_BATCH_SIZE, 500]
    assert (out["remaining"] == np.arange(MAX_BATCH_SIZE + 500)).all()


def test_multinode_hello_carries_ring_and_bridge_endpoints():
    """With >1 peers and a TCP listener configured, the hello must
    advertise the fast path plus every node's bridge endpoint (peer
    gRPC host + this node's TCP port — the symmetric-fleet convention),
    with an empty endpoint for self (the edge uses its --backend)."""

    class FakePicker:
        def peers(self):
            return [
                FakePeer("10.0.0.2:81"),
                FakePeer("10.0.0.1:81", is_owner=True),
            ]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()

    async def run():
        path = "/tmp/guber-bridge-ring-hello.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        # set after start: only the hello's endpoint derivation reads
        # it here; the real TCP listener is covered by the cluster e2e
        # (tests/test_edge_cluster.py)
        bridge.tcp_address = "0.0.0.0:9470"
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, rhash, nodes = await _read_hello(reader)
            writer.close()
            return flags, rhash, nodes
        finally:
            await bridge.stop()

    flags, rhash, nodes = asyncio.run(run())
    assert flags == 1  # fast path stays on in a cluster (r5)
    assert rhash == ring_fingerprint(["10.0.0.1:81", "10.0.0.2:81"])
    # sorted by gRPC address; self has no bridge endpoint, the peer's is
    # derived from its host + our TCP port
    assert nodes == [
        (True, "10.0.0.1:81", ""),
        (False, "10.0.0.2:81", "10.0.0.2:9470"),
    ]


def test_stale_ring_fast_frame_refused_with_gebr():
    """A GEB6 frame whose ring fingerprint does not match the live
    membership must be answered with GEBR and the connection closed —
    deciding it locally could admit keys this node no longer owns
    (the r5 replacement for r4's fast-path-off-in-clusters gate)."""
    import numpy as np

    from gubernator_tpu.serve.edge_bridge import MAGIC_STALE, _fast_dtypes

    class FakePicker:
        def peers(self):
            return [
                FakePeer("10.0.0.1:81", is_owner=True),
                FakePeer("10.0.0.2:81"),
            ]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()
        traffic = _FakeTraffic()

    async def run():
        path = "/tmp/guber-bridge-stale-ring.sock"
        bridge = EdgeBridge(FakeInstance(), path)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, rhash, _nodes = await _read_hello(reader)
            assert flags == 1
            req_dt, _ = _fast_dtypes()
            rec = np.zeros(2, req_dt)
            rec["key_hash"] = [1, 2]
            stale = (rhash + 1) & 0xFFFFFFFF
            writer.write(_fast_frame(rec, stale))
            await writer.drain()
            magic, n = struct.unpack("<II", await reader.readexactly(8))
            assert magic == MAGIC_STALE and n == 0
            got = await reader.read(8)
            assert got == b"", got  # bridge closed after GEBR
            writer.close()
        finally:
            await bridge.stop()

    asyncio.run(run())


def test_fast_kill_switch_unadvertises():
    """GUBER_EDGE_FAST=0 (EdgeBridge fast_enabled=False) must stop
    advertising the pre-hashed path in the hello — the operational
    fallback that forces every edge item through the full instance."""

    class FakePicker:
        def peers(self):
            return [FakePeer("127.0.0.1:81", is_owner=True)]

    class FakeInstance:
        backend = _FakeBackendArrays()
        picker = FakePicker()

    async def run():
        path = "/tmp/guber-bridge-killswitch.sock"
        bridge = EdgeBridge(FakeInstance(), path, fast_enabled=False)
        await bridge.start()
        try:
            reader, writer = await asyncio.open_unix_connection(path)
            flags, _rhash, _nodes = await _read_hello(reader)
            writer.close()
            return flags
        finally:
            await bridge.stop()

    assert asyncio.run(run()) == 0
