"""Vendored etcd client + EtcdPool live round trips over real gRPC.

r3's gap: the etcd path existed but had never executed live (the etcd3
library is absent from this image). Now the vendored client
(serve/etcd_client.py) runs the full lease+put+watch+re-register cycle
against tests/_fake_etcd.py — a real grpc server speaking the vendored
etcd protos — and, when GUBER_TEST_ETCD names a live endpoint, against
real etcd with the same assertions. The skip reason distinguishes "no
etcd available" from "never tried": the fake-backed tests always run.
"""

import asyncio
import os
import threading
import time

import pytest

from gubernator_tpu.serve.etcd_client import (
    VendoredEtcdClient,
    prefix_range_end,
)
from tests._fake_etcd import FakeEtcd

REAL_ETCD = os.environ.get("GUBER_TEST_ETCD", "")


@pytest.fixture()
def fake():
    srv = FakeEtcd().start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(fake):
    c = VendoredEtcdClient(host="127.0.0.1", port=fake.port)
    yield c
    c.close()


def test_prefix_range_end_convention():
    assert prefix_range_end(b"/guber/") == b"/guber0"
    assert prefix_range_end(b"a\xff") == b"b"
    assert prefix_range_end(b"\xff\xff") == b"\0"


def test_put_get_delete_roundtrip(client):
    client.put("/t/a", "A")
    client.put("/t/b", b"B")
    client.put("/u/other", "X")
    got = client.get_prefix("/t/")
    assert sorted(v for v, _m in got) == [b"A", b"B"]
    keys = sorted(m.key for _v, m in got)
    assert keys == [b"/t/a", b"/t/b"]
    assert client.delete("/t/a") is True
    assert client.delete("/t/a") is False
    assert [v for v, _m in client.get_prefix("/t/")] == [b"B"]


def test_lease_lifecycle_and_keepalive(client, fake):
    lease = client.lease(30)
    assert lease.id in fake.lease_ids()
    client.put("/l/me", "me", lease=lease)
    lease.refresh()  # alive: no raise
    fake.revoke_lease(lease.id)
    # expiry drops the attached key, and refresh now fails loudly
    assert client.get_prefix("/l/") == []
    with pytest.raises(RuntimeError, match="expired"):
        lease.refresh()


def test_watch_prefix_sees_put_and_delete(client):
    events, cancel = client.watch_prefix("/w/")
    got = []
    done = threading.Event()

    def consume():
        for ev in events:
            got.append((ev.type, bytes(ev.kv.key)))
            if len(got) >= 2:
                break
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)  # let the watch register
    client.put("/w/k1", "v1")
    client.delete("/w/k1")
    assert done.wait(timeout=10), got
    assert got[0][1] == b"/w/k1" and got[1][1] == b"/w/k1"
    assert got[0][0] == 0 and got[1][0] == 1  # PUT then DELETE
    cancel()
    t.join(timeout=5)


def test_watch_cancel_unblocks(client):
    events, cancel = client.watch_prefix("/wc/")
    finished = threading.Event()

    def consume():
        for _ in events:
            pass
        finished.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    cancel()
    assert finished.wait(timeout=10)


def _run_pool_cycle(client, fake_or_none):
    """Full EtcdPool membership cycle on a real event loop."""
    from gubernator_tpu.serve.discovery import EtcdPool

    updates = []

    async def scenario():
        seen = asyncio.Event()

        async def on_update(peers):
            updates.append(sorted(p.address for p in peers))
            seen.set()

        pool = EtcdPool(
            endpoints=["unused:0"],
            prefix="/guber-test/peers/",
            advertise="10.0.0.1:81",
            on_update=on_update,
            client=client,
        )
        await pool.start()
        try:
            assert updates[-1] == ["10.0.0.1:81"]

            # events before the watch stream finishes registering are
            # not delivered (same contract as clientv3/etcd3 watches) —
            # wait for registration before acting
            if fake_or_none is not None:
                for _ in range(250):
                    if fake_or_none._watches:
                        break
                    await asyncio.sleep(0.02)
                assert fake_or_none._watches, "watch never registered"
            else:
                await asyncio.sleep(0.5)

            # a second node registers out-of-band: the watch pushes it
            seen.clear()
            lease2 = client.lease(30)
            client.put(
                "/guber-test/peers/10.0.0.2:81", "10.0.0.2:81",
                lease=lease2,
            )
            await asyncio.wait_for(seen.wait(), timeout=10)
            assert updates[-1] == ["10.0.0.1:81", "10.0.0.2:81"]

            # and its departure (lease revoke = expiry) pushes again
            seen.clear()
            lease2.revoke()
            await asyncio.wait_for(seen.wait(), timeout=10)
            assert updates[-1] == ["10.0.0.1:81"]

            if fake_or_none is not None:
                # lease-loss failure injection: revoke OUR lease behind
                # the pool's back, drive the keepalive path directly
                # (the loop fires at TTL/3 = 10s — too slow for a test),
                # and assert the pool re-registered (etcd.go:247-301)
                fake_or_none.revoke_lease(pool._lease.id)
                assert client.get_prefix("/guber-test/peers/") == []
                with pytest.raises(Exception):
                    pool._lease.refresh()
                await asyncio.to_thread(pool._register)
                vals = [
                    v.decode()
                    for v, _m in client.get_prefix("/guber-test/peers/")
                ]
                assert vals == ["10.0.0.1:81"]
        finally:
            # ALWAYS close: a dangling watch worker would wedge
            # asyncio.run's executor shutdown after a failure
            await pool.close()
        # close deletes the registration key
        assert client.get_prefix("/guber-test/peers/") == []

    asyncio.run(scenario())


def test_pool_full_cycle_against_fake(client, fake):
    _run_pool_cycle(client, fake)


@pytest.mark.skipif(
    not REAL_ETCD,
    reason="no etcd available (set GUBER_TEST_ETCD=host:port to run "
    "against a live cluster; the fake-backed cycle above always runs)",
)
def test_pool_full_cycle_against_real_etcd():
    host, _, port = REAL_ETCD.rpartition(":")
    c = VendoredEtcdClient(host=host or "127.0.0.1", port=int(port))
    try:
        _run_pool_cycle(c, None)
    finally:
        c.close()
