"""Edge fuzz + e2e suites under AddressSanitizer/UBSan.

The edge hand-rolls parsers for everything a client controls (HTTP/1.1
headers, JSON bodies, HTTP/2 frames, HPACK dynamic tables + Huffman,
protobuf) — the exact surfaces where a heap overflow that happens not
to crash is invisible to functional tests. The reference gets memory
safety for free from Go (its front end cannot heap-overflow); this
tier earns it by running the SAME fuzz corpora and e2e drives against
a `-fsanitize=address,undefined -fno-sanitize-recover=all` build: any
OOB/UB aborts the edge, which the inner suites detect as a dead
process.

Build: `make -C gubernator_tpu/native/edge asan` (done here if the
binary is missing or stale). The inner pytest run reuses the real
suites via GUBER_EDGE_BIN (tests/_util.edge_binary), so sanitizer
coverage tracks the corpora as they grow instead of forking them.
"""

import os
import pathlib
import shutil
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
EDGE_DIR = ROOT / "gubernator_tpu" / "native" / "edge"
ASAN_BIN = EDGE_DIR / "guber-edge-asan"

# the sanitized run re-executes whole suites; keep it in one module-
# scoped build + two inner pytest invocations
pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)


@pytest.fixture(scope="module")
def asan_bin(tmp_path_factory):
    # probe whether this toolchain can BUILD AND LINK with the
    # sanitizers at all (musl g++ or missing libasan/libubsan runtime
    # packages are environment limitations, not code regressions)
    probe_dir = tmp_path_factory.mktemp("asan-probe")
    probe_src = probe_dir / "p.cc"
    probe_src.write_text("int main() { return 0; }\n")
    probe = subprocess.run(
        ["g++", "-fsanitize=address,undefined", "-o",
         str(probe_dir / "p"), str(probe_src)],
        capture_output=True,
        text=True,
    )
    if probe.returncode != 0:
        pytest.skip(f"sanitizer runtime unavailable:\n{probe.stderr[-500:]}")
    build = subprocess.run(
        ["make", "-C", str(EDGE_DIR), "asan"],
        capture_output=True,
        text=True,
    )
    if build.returncode != 0:
        # the probe proved sanitizers work here, so a build break under
        # ASANFLAGS is a CODE regression and must FAIL — a skip would
        # silently remove all sanitizer coverage
        pytest.fail(f"asan build failed:\n{build.stderr[-2000:]}")
    assert ASAN_BIN.exists()
    return ASAN_BIN


def _run_suites_under_asan(asan_bin, modules):
    env = dict(
        os.environ,
        GUBER_EDGE_BIN=str(asan_bin),
        # abort (not exit) on any report so the driving suite sees a
        # dead edge; leak checking is off — the edge's shutdown path is
        # _exit/SIGKILL by design, and LSan would flag the still-live
        # detached-lane allocations as leaks on every teardown
        ASAN_OPTIONS="abort_on_error=1:detect_leaks=0",
        UBSAN_OPTIONS="abort_on_error=1:print_stacktrace=1",
    )
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q", *modules],
        cwd=ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, (
        f"suites failed under ASan/UBSan:\n{proc.stdout[-4000:]}\n"
        f"{proc.stderr[-2000:]}"
    )
    return proc.stdout


def test_fuzz_corpora_clean_under_asan(asan_bin):
    """Both fuzz suites (HTTP/JSON and gRPC/h2/HPACK) drive the
    sanitized binary: garbage frames, truncated bodies, malformed
    Huffman, oversized fields — all must parse or fail WITHOUT a
    single OOB/UB report."""
    out = _run_suites_under_asan(
        asan_bin,
        ["tests/test_edge_fuzz.py", "tests/test_edge_grpc_fuzz.py"],
    )
    assert " passed" in out


def test_e2e_doors_clean_under_asan(asan_bin):
    """The functional doors (HTTP + gRPC termination, fast path,
    cluster routing) under the sanitized build: exercises the
    steady-state codepaths the fuzzers skip (HPACK dynamic-table
    reuse across requests, GEB6 framing, ring routing)."""
    out = _run_suites_under_asan(
        asan_bin,
        [
            "tests/test_edge.py",
            "tests/test_edge_grpc.py",
            "tests/test_edge_cluster.py",
            "tests/test_edge_ring_change.py",
            # the churn soak concentrates the lane eviction/refresh
            # concurrency — exactly where a lifetime bug (use-after-
            # free of an evicted Lane, a racing shard) would hide from
            # functional tests but abort under ASan
            "tests/test_edge_churn_soak.py",
        ],
    )
    assert " passed" in out
