"""Cross-protocol decision identity (r12): the same request stream
through the gRPC protobuf door, the GEB client protocol, and the HTTP
binary door must return byte-identical decisions.

Three separate single-node stacks (one per protocol, own device store
each) replay one fuzz stream — mixed algorithms, duplicate keys,
peeks, over-limit freezes, clock advances across reset boundaries —
under the shared fake clock pattern from r10 (every now() import site
pinned), so reset_time compares EXACTLY. The GEB door negotiates FAST
framing (single-node ring, matching hash tier), which makes this the
client-side hash-parity contract too: a client-hashed GEB7 record must
land in the same store row as the daemon-hashed gRPC path's.

tpu backend on CPU end to end: instance -> batcher -> arrival prep ->
merged submit -> kernel, per the r10 device-fuzz pattern.
"""

import asyncio

import numpy as np
import pytest

from _util import free_ports
from gubernator_tpu.api.types import Algorithm, RateLimitReq
from gubernator_tpu.cluster import LocalCluster
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve.backends import TpuBackend

T0 = 1_700_000_000_000


class FakeClock:
    def __init__(self):
        self.t = T0

    def __call__(self):
        return self.t


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


def _be():
    return TpuBackend(
        StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
    )


def _fuzz_stream(rng, keys, steps):
    for step in range(steps):
        n = int(rng.integers(1, 7))
        batch = []
        for _ in range(n):
            k = int(rng.integers(len(keys)))
            batch.append(
                RateLimitReq(
                    name="xdoor",
                    unique_key=keys[k],
                    hits=int(rng.choice([0, 1, 1, 1, 2, 9])),
                    limit=int(rng.choice([1, 2, 3, 50])),
                    duration=int(rng.choice([400, 2000, 60_000])),
                    algorithm=Algorithm(k % 2),
                )
            )
        yield step, batch, int(rng.choice([0, 0, 1, 7, 150, 500, 2500]))


def test_three_door_identity_fuzz(monkeypatch):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    ports = free_ports(6)
    grpc_addrs = [f"127.0.0.1:{p}" for p in ports[:3]]
    http_addr = f"127.0.0.1:{ports[3]}"
    geb_port = ports[4]

    clusters = [
        # door 0: gRPC; door 1: GEB listener; door 2: HTTP binary
        LocalCluster([grpc_addrs[0]], backend_factory=_be),
        LocalCluster(
            [grpc_addrs[1]], backend_factory=_be, geb_ports=[geb_port]
        ),
        LocalCluster(
            [grpc_addrs[2]], backend_factory=_be,
            http_addresses=[http_addr],
        ),
    ]
    for c in clusters:
        c.start()
        # the shed caches must read the fake clock too (the r10
        # in-process pattern) or expiry gates would diverge
        inst = c.servers[0].instance
        if inst.shed is not None:
            inst.shed.now_fn = clock
    try:

        async def run():
            from gubernator_tpu.client import AsyncV1Client
            from gubernator_tpu.client_geb import (
                AsyncGebClient,
                AsyncHttpGebClient,
            )

            grpc_c = AsyncV1Client(grpc_addrs[0])
            geb_c = AsyncGebClient(f"127.0.0.1:{geb_port}")
            http_c = AsyncHttpGebClient(f"http://{http_addr}")
            await geb_c.connect()
            # the point of the exercise: the GEB door negotiated the
            # pre-hashed fast path (client-side hashing under test)
            assert geb_c._use_fast
            rng = np.random.default_rng(13)
            keys = [f"xk{i}" for i in range(12)]
            mismatches = []
            try:
                for step, batch, dt in _fuzz_stream(rng, keys, 90):
                    clock.t += dt
                    a = await grpc_c.get_rate_limits(batch)
                    b = await geb_c.get_rate_limits(batch)
                    d = await http_c.get_rate_limits(batch)
                    for i, (x, y, z) in enumerate(zip(a, b, d)):
                        tup = lambda r: (  # noqa: E731
                            int(r.status), r.limit, r.remaining,
                            r.reset_time, r.error,
                        )
                        if not (tup(x) == tup(y) == tup(z)):
                            mismatches.append(
                                (step, i, batch[i], tup(x), tup(y),
                                 tup(z))
                            )
            finally:
                await grpc_c.close()
                await geb_c.close()
                await http_c.close()
            return mismatches

        mismatches = asyncio.run(run())
        assert not mismatches, mismatches[:5]
    finally:
        for c in clusters:
            c.stop()


def test_geb_fast_vs_string_mode_identity(monkeypatch):
    """The SAME door, fast vs string framing, two fresh stores: the
    client-side pre-hash plus array path must decide identically to
    the server-side object path for fast-eligible traffic."""
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    ports = free_ports(4)
    clusters = [
        LocalCluster(
            [f"127.0.0.1:{ports[i]}"], backend_factory=_be,
            geb_ports=[ports[i + 2]],
        )
        for i in range(2)
    ]
    for c in clusters:
        c.start()
        inst = c.servers[0].instance
        if inst.shed is not None:
            inst.shed.now_fn = clock
    try:

        async def run():
            from gubernator_tpu.client_geb import AsyncGebClient

            fast = AsyncGebClient(f"127.0.0.1:{ports[2]}", mode="fast")
            string = AsyncGebClient(
                f"127.0.0.1:{ports[3]}", mode="string"
            )
            rng = np.random.default_rng(29)
            keys = [f"fs{i}" for i in range(10)]
            try:
                await fast.connect()
                for step, batch, dt in _fuzz_stream(rng, keys, 70):
                    clock.t += dt
                    a = await fast.get_rate_limits(batch)
                    b = await string.get_rate_limits(batch)
                    for x, y, r in zip(a, b, batch):
                        assert (
                            int(x.status), x.limit, x.remaining,
                            x.reset_time,
                        ) == (
                            int(y.status), y.limit, y.remaining,
                            y.reset_time,
                        ), (step, r, x, y)
            finally:
                await fast.close()
                await string.close()

        asyncio.run(run())
    finally:
        for c in clusters:
            c.stop()
