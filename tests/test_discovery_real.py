"""Real-library discovery contract tests (gated: skip when the packages
are absent, as in this builder image).

These run wherever `pip install .[discovery]` has been done and pin the
REAL etcd3/kubernetes client surfaces against the same contract the
fakes are pinned to (tests/_discovery_contract.py) — closing the r2 gap
where serve/discovery.py had only ever executed against fakes written
from the same mental model as the code under test.

Optionally, with a reachable etcd (GUBER_TEST_ETCD=host:port) the etcd
pool runs a real register/watch/deregister round trip.
"""

import asyncio
import os

import pytest

from _discovery_contract import (
    ETCD_CLIENT_CALLS,
    ETCD_CLIENT_CTOR_CALL,
    ETCD_LEASE_CALLS,
    K8S_API_CALLS,
    K8S_ENDPOINTS_ATTRS,
    K8S_WATCH_CALLS,
    assert_binds,
    assert_object_implements,
)


def _import_etcd3():
    """importorskip, but also skipping on the known non-ImportError
    failure mode: etcd3 0.12.x's generated pb2 modules raise TypeError
    under protobuf>=4 (see the pyproject discovery extra's co-pin)."""
    try:
        return pytest.importorskip("etcd3")
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"etcd3 present but unimportable: {e}")


def test_real_etcd3_client_matches_contract():
    etcd3 = _import_etcd3()
    assert_binds(etcd3.client, ETCD_CLIENT_CTOR_CALL, "etcd3.client")
    assert_object_implements(
        etcd3.Etcd3Client, ETCD_CLIENT_CALLS, "Etcd3Client", unbound=True
    )
    assert_object_implements(
        etcd3.Lease, ETCD_LEASE_CALLS, "Lease", unbound=True
    )


def test_real_kubernetes_client_matches_contract():
    kubernetes = pytest.importorskip("kubernetes")
    assert_object_implements(
        kubernetes.client.CoreV1Api, K8S_API_CALLS, "CoreV1Api",
        unbound=True,
    )
    assert_object_implements(
        kubernetes.watch.Watch, K8S_WATCH_CALLS, "Watch", unbound=True
    )
    # the attribute path _push reads: V1Endpoints.subsets[].addresses[].ip
    m = kubernetes.client.models
    assert "subsets" in m.V1Endpoints.attribute_map, K8S_ENDPOINTS_ATTRS
    assert "addresses" in m.V1EndpointSubset.attribute_map
    assert "ip" in m.V1EndpointAddress.attribute_map
    # and the incluster config loader the pool calls
    assert callable(kubernetes.config.load_incluster_config)


def test_real_etcd_round_trip():
    """Full register/watch/deregister against a real etcd server; runs
    only where GUBER_TEST_ETCD points at one."""
    _import_etcd3()
    endpoint = os.environ.get("GUBER_TEST_ETCD")
    if not endpoint:
        pytest.skip("set GUBER_TEST_ETCD=host:port to run against etcd")

    from gubernator_tpu.serve.discovery import EtcdPool

    seen = []

    async def on_update(peers):
        seen.append([p.address for p in peers])

    async def main():
        pool = EtcdPool(
            [endpoint], "/guber-test/peers/", "10.0.0.1:81", on_update
        )
        await pool.start()
        await asyncio.sleep(0.5)
        await pool.close()

    asyncio.run(asyncio.wait_for(main(), timeout=30))
    assert any("10.0.0.1:81" in s for s in seen), seen
